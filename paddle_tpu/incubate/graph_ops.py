"""Graph-learning message passing (reference:
python/paddle/incubate/operators/graph_send_recv.py:22 graph_send_recv).

The reference lowers to a dedicated CUDA scatter-reduce kernel
(operators/graph_send_recv_op.cu); on TPU the same semantics are XLA
segment reductions — gather rows by ``src_index``, segment-reduce into
``dst_index`` — which fuse into the surrounding program instead of a
standalone kernel.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = ["graph_send_recv"]

_POOLS = ("sum", "mean", "max", "min")


def graph_send_recv(x, src_index, dst_index, pool_type: str = "sum",
                    out_size: Optional[int] = None, name=None):
    """Gather ``x[src_index]`` and scatter-reduce into row ``dst_index``.

    Rows of the output that receive no message are 0 (reference kernel
    initializes the output buffer to zeros for every pool type).
    ``out_size`` fixes the number of output rows (defaults to
    ``x.shape[0]``, the reference default).
    """
    enforce(pool_type in _POOLS,
            f"pool_type must be one of {_POOLS}, got {pool_type!r}")
    x = jnp.asarray(x)
    src = jnp.asarray(src_index, jnp.int32)
    dst = jnp.asarray(dst_index, jnp.int32)
    enforce(src.ndim == 1 and dst.ndim == 1 and src.shape == dst.shape,
            f"src/dst_index must be equal-length 1-D, got {src.shape} "
            f"vs {dst.shape}")
    n = int(out_size) if out_size is not None else x.shape[0]
    gathered = x[src]
    if pool_type == "sum":
        return jax.ops.segment_sum(gathered, dst, num_segments=n)
    counts = jax.ops.segment_sum(jnp.ones_like(dst, x.dtype), dst,
                                 num_segments=n)
    if pool_type == "mean":
        s = jax.ops.segment_sum(gathered, dst, num_segments=n)
        denom = jnp.maximum(counts, 1).reshape((-1,) + (1,) * (x.ndim - 1))
        return s / denom
    if pool_type == "max":
        r = jax.ops.segment_max(gathered, dst, num_segments=n)
    else:
        r = jax.ops.segment_min(gathered, dst, num_segments=n)
    # empty segments come back +/-inf from XLA; the reference zero-fills
    empty = (counts == 0).reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(empty, jnp.zeros_like(r), r)


def _num_segments(segment_ids) -> int:
    """max(id) + 1 as a STATIC int.  The output shape depends on it, so
    it must be concrete: host numpy when ids are concrete (incl. numpy
    constants closed over by a jit trace); a traced-ids call gets a
    typed error (the reference's is likewise an eager dynamic-shape op)."""
    import numpy as np

    import jax.errors
    try:
        ids = np.asarray(segment_ids)
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        enforce(False,
                "segment ops need concrete segment_ids (the output length "
                "max(id)+1 is a shape): pass numpy/host ids, or keep the "
                "op outside jit")
    enforce(ids.size > 0, "segment ops need at least one segment id")
    return int(ids.max()) + 1


def segment_sum(data, segment_ids):
    """Segment reduction over dim 0 (reference incubate segment_sum;
    XLA-native via jax.ops.segment_*)."""
    import jax.numpy as jnp
    n = _num_segments(segment_ids)
    return jax.ops.segment_sum(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def segment_mean(data, segment_ids):
    import jax.numpy as jnp
    data = jnp.asarray(data)
    n = _num_segments(segment_ids)
    ids = jnp.asarray(segment_ids)
    s = jax.ops.segment_sum(data, ids, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones(data.shape[:1], data.dtype), ids,
                            num_segments=n)
    shape = (-1,) + (1,) * (data.ndim - 1)
    return s / jnp.maximum(c.reshape(shape), 1)


def segment_max(data, segment_ids):
    import jax.numpy as jnp
    n = _num_segments(segment_ids)
    return jax.ops.segment_max(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def segment_min(data, segment_ids):
    import jax.numpy as jnp
    n = _num_segments(segment_ids)
    return jax.ops.segment_min(jnp.asarray(data), jnp.asarray(segment_ids),
                               num_segments=n)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids: bool = False):
    """K-hop neighbor sampling over a CSC graph (reference incubate
    graph_khop_sampler: returns (edge_src, edge_dst, sample_index,
    reindex_nodes[, edge_eids])).  Host-side numpy sampling — graph
    prep, not traced compute (the reference's is an eager op).
    ``sample_index`` lists unique touched nodes with the INPUT nodes
    first (first-seen order); ``reindex_nodes`` gives the input nodes'
    positions in it."""
    import numpy as np
    rng = np.random
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    inputs = np.asarray(input_nodes).reshape(-1)
    frontier = inputs
    all_rows, all_cols, all_eids = [], [], []
    for k in sample_sizes:
        rs, cs, es = [], [], []
        for dst in frontier:
            lo, hi = int(colptr[dst]), int(colptr[dst + 1])
            neigh = row[lo:hi]
            eid = (np.asarray(sorted_eids)[lo:hi] if sorted_eids is not None
                   else np.arange(lo, hi))
            if k >= 0 and len(neigh) > k:
                sel = rng.choice(len(neigh), k, replace=False)
                neigh, eid = neigh[sel], eid[sel]
            rs.append(neigh)
            cs.append(np.full(len(neigh), dst, row.dtype))
            es.append(eid)
        rs = np.concatenate(rs) if rs else np.empty(0, row.dtype)
        cs = np.concatenate(cs) if cs else np.empty(0, row.dtype)
        es = np.concatenate(es) if es else np.empty(0, np.int64)
        all_rows.append(rs); all_cols.append(cs); all_eids.append(es)
        frontier = np.unique(rs)
    rows = np.concatenate(all_rows)
    cols = np.concatenate(all_cols)
    # reindex in first-seen order with input nodes leading (reference
    # contract: inputs occupy the head of sample_index)
    mapping = {}
    sample_index = []
    for v in np.concatenate([inputs, cols, rows]):
        v = int(v)
        if v not in mapping:
            mapping[v] = len(sample_index)
            sample_index.append(v)
    r_re = np.asarray([mapping[int(v)] for v in rows], np.int64)
    c_re = np.asarray([mapping[int(v)] for v in cols], np.int64)
    reindex_nodes = np.arange(len(inputs), dtype=np.int64)
    out = (jnp.asarray(r_re), jnp.asarray(c_re),
           jnp.asarray(np.asarray(sample_index, np.int64)),
           jnp.asarray(reindex_nodes))
    if return_eids:
        return out + (jnp.asarray(np.concatenate(all_eids)),)
    return out


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size: int = -1,
                           return_eids: bool = False,
                           flag_perm_buffer: bool = False):
    """One-hop neighbor sampling (reference graph_sample_neighbors)."""
    import numpy as np
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    outs, counts, es = [], [], []
    for dst in np.asarray(input_nodes).reshape(-1):
        lo, hi = int(colptr[dst]), int(colptr[dst + 1])
        neigh = row[lo:hi]
        eid = np.arange(lo, hi)
        if sample_size >= 0 and len(neigh) > sample_size:
            sel = np.random.choice(len(neigh), sample_size, replace=False)
            neigh, eid = neigh[sel], eid[sel]
        outs.append(neigh); counts.append(len(neigh)); es.append(eid)
    out = np.concatenate(outs) if outs else np.empty(0, row.dtype)
    cnt = np.asarray(counts, np.int32)
    if return_eids:
        return (jnp.asarray(out), jnp.asarray(cnt),
                jnp.asarray(np.concatenate(es) if es else np.empty(0)))
    return jnp.asarray(out), jnp.asarray(cnt)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable: bool = False):
    """Reindex a sampled subgraph to contiguous ids (reference
    graph_reindex): x (dst nodes) keep ids 0..n-1; new neighbor ids
    follow in first-seen order."""
    import numpy as np
    x = np.asarray(x).reshape(-1)
    neighbors = np.asarray(neighbors).reshape(-1)
    count = np.asarray(count).reshape(-1)
    mapping = {int(v): i for i, v in enumerate(x)}
    out_nodes = list(x)
    reindexed = np.empty(len(neighbors), np.int64)
    for i, v in enumerate(neighbors):
        v = int(v)
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
        reindexed[i] = mapping[v]
    # reindexed dst per neighbor: repeat each x by its count
    dst = np.repeat(np.arange(len(x), dtype=np.int64), count)
    return (jnp.asarray(reindexed), jnp.asarray(dst),
            jnp.asarray(np.asarray(out_nodes, np.int64)))
