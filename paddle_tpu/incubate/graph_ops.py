"""Graph-learning message passing (reference:
python/paddle/incubate/operators/graph_send_recv.py:22 graph_send_recv).

The reference lowers to a dedicated CUDA scatter-reduce kernel
(operators/graph_send_recv_op.cu); on TPU the same semantics are XLA
segment reductions — gather rows by ``src_index``, segment-reduce into
``dst_index`` — which fuse into the surrounding program instead of a
standalone kernel.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = ["graph_send_recv"]

_POOLS = ("sum", "mean", "max", "min")


def graph_send_recv(x, src_index, dst_index, pool_type: str = "sum",
                    out_size: Optional[int] = None, name=None):
    """Gather ``x[src_index]`` and scatter-reduce into row ``dst_index``.

    Rows of the output that receive no message are 0 (reference kernel
    initializes the output buffer to zeros for every pool type).
    ``out_size`` fixes the number of output rows (defaults to
    ``x.shape[0]``, the reference default).
    """
    enforce(pool_type in _POOLS,
            f"pool_type must be one of {_POOLS}, got {pool_type!r}")
    x = jnp.asarray(x)
    src = jnp.asarray(src_index, jnp.int32)
    dst = jnp.asarray(dst_index, jnp.int32)
    enforce(src.ndim == 1 and dst.ndim == 1 and src.shape == dst.shape,
            f"src/dst_index must be equal-length 1-D, got {src.shape} "
            f"vs {dst.shape}")
    n = int(out_size) if out_size is not None else x.shape[0]
    gathered = x[src]
    if pool_type == "sum":
        return jax.ops.segment_sum(gathered, dst, num_segments=n)
    counts = jax.ops.segment_sum(jnp.ones_like(dst, x.dtype), dst,
                                 num_segments=n)
    if pool_type == "mean":
        s = jax.ops.segment_sum(gathered, dst, num_segments=n)
        denom = jnp.maximum(counts, 1).reshape((-1,) + (1,) * (x.ndim - 1))
        return s / denom
    if pool_type == "max":
        r = jax.ops.segment_max(gathered, dst, num_segments=n)
    else:
        r = jax.ops.segment_min(gathered, dst, num_segments=n)
    # empty segments come back +/-inf from XLA; the reference zero-fills
    empty = (counts == 0).reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(empty, jnp.zeros_like(r), r)
