"""incubate.nn fused transformer layers (reference:
incubate/nn/layer/fused_transformer.py — FusedMultiHeadAttention backed by
fused_attention_op.cc:221, FusedFeedForward backed by
fused_feedforward_op.cu).

Here the "fusion" is real on TPU too: each layer is one XLA region (and
attention routes to the Pallas flash kernel when eligible), so the
reference's hand-fused CUDA graph becomes compiler-fused MXU code.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers import LayerNorm, Linear
from ..ops import fused as fused_ops

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward"]


class FusedMultiHeadAttention(Layer):
    """pre/post-LN → fused QKV GEMM → FMHA → out proj →
    bias+dropout+residual(+LN) — fused_attention_op.cc:221-357 semantics."""

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout_rate: float = 0.5, attn_dropout_rate: float = 0.5,
                 normalize_before: bool = False, epsilon: float = 1e-5,
                 dtype="float32"):
        super().__init__()
        from ..framework.errors import enforce
        enforce(num_heads > 0 and embed_dim % num_heads == 0,
                f"num_heads must be positive and divide embed_dim "
                f"(got num_heads={num_heads}, embed_dim={embed_dim})")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        # one fused (3E) projection — the qkv GEMM of the reference op
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim, dtype=dtype)
        self.out_proj = Linear(embed_dim, embed_dim, dtype=dtype)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon, dtype=dtype)

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        b, s, _ = x.shape
        qkv = self.qkv_proj(x).reshape(b, s, 3, self.num_heads,
                                       self.head_dim)
        q, k, v = (jnp.swapaxes(qkv[:, :, i], 1, 2) for i in range(3))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        out = jnp.swapaxes(out, 1, 2).reshape(b, s, self.embed_dim)
        out = F.linear(out, self.out_proj.weight, None)
        out = fused_ops.fused_bias_dropout_residual(
            out, residual, self.out_proj.bias, self.dropout_rate,
            self.training)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    """pre/post-LN → GEMM+act(+dropout) → GEMM → bias+dropout+residual —
    fused_feedforward_op semantics via ops.fused.fused_feedforward."""

    def __init__(self, d_model: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, activation: str = "relu",
                 act_dropout_rate: Optional[float] = None,
                 normalize_before: bool = False, epsilon: float = 1e-5,
                 dtype="float32"):
        super().__init__()
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate \
            if act_dropout_rate is not None else dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm = LayerNorm(d_model, epsilon=epsilon, dtype=dtype)

    def forward(self, x):
        return fused_ops.fused_feedforward(
            x, self.linear1.weight, self.linear1.bias, self.linear2.weight,
            self.linear2.bias, self.norm.weight, self.norm.bias,
            activation=self.activation, dropout1=self.act_dropout_rate,
            dropout2=self.dropout_rate, epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Reference incubate/nn FusedTransformerEncoderLayer: the fused MHA +
    fused FFN pair as one encoder block (fused_transformer.py)."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout_rate: float = 0.1, activation: str = "relu",
                 attn_dropout_rate: Optional[float] = None,
                 act_dropout_rate: Optional[float] = None,
                 normalize_before: bool = False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate
                               if attn_dropout_rate is not None
                               else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=(act_dropout_rate
                              if act_dropout_rate is not None
                              else dropout_rate),
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


__all__.append("FusedTransformerEncoderLayer")
