"""Autograd (reference: python/paddle/autograd/ — PyLayer py_layer.py:202,
functional vjp/jvp/jacobian/hessian functional.py:22-1133; engine
paddle/fluid/imperative/basic_engine.cc).

On TPU, autodiff is JAX's transform — there is no tape/engine to build (the
reference's BasicEngine/GradNode graph collapses into jax.grad).  This module
provides the paddle-shaped entry points plus a PyLayer built on
jax.custom_vjp for user-defined gradients (used by recompute, ZeRO-3 hooks in
the reference).
"""
from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

__all__ = ["grad", "value_and_grad", "vjp", "jvp", "jacobian", "hessian",
           "PyLayer", "no_grad", "backward"]

# functional autograd — direct jax transforms
vjp = jax.vjp
jvp = jax.jvp
jacobian = jax.jacrev
hessian = jax.hessian


def grad(fn: Callable, argnums=0, has_aux: bool = False):
    return jax.grad(fn, argnums=argnums, has_aux=has_aux)


def value_and_grad(fn: Callable, argnums=0, has_aux: bool = False):
    return jax.value_and_grad(fn, argnums=argnums, has_aux=has_aux)


class no_grad:
    """Context/decorator: stop gradients through the wrapped computation.
    In functional JAX there is no global tape; this is provided for API parity
    and wraps outputs in stop_gradient when used as a decorator.  Inside the
    context ``paddle_tpu.is_grad_enabled()`` reports False (reference
    dygraph/base.py interplay)."""

    def __init__(self):
        from ..framework.mode import set_grad_enabled
        # one stateful cm whose internal stack makes this instance safely
        # re-enterable (nested `with ng`, recursive decorated functions)
        self._cm = set_grad_enabled(False)

    def __enter__(self):
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with self:
                out = fn(*args, **kwargs)
            return jax.tree_util.tree_map(jax.lax.stop_gradient, out)
        return wrapper


class PyLayerContext:
    """Reference: autograd/py_layer.py:23 PyLayerContext."""

    def __init__(self):
        self._saved = ()
        self.attrs: Dict[str, Any] = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    saved_tensors = saved_tensor


class _PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)
        if name == "PyLayer":
            return

        @jax.custom_vjp
        def _call(*args):
            ctx = PyLayerContext()
            return cls.forward(ctx, *args)

        def _fwd(*args):
            ctx = PyLayerContext()
            out = cls.forward(ctx, *args)
            # residuals must be JAX pytrees: keep only the saved tensors
            return out, (ctx._saved, args)

        def _bwd(res, g):
            saved, args = res
            ctx = PyLayerContext()
            ctx._saved = saved
            grads = cls.backward(ctx, g)
            if not isinstance(grads, tuple):
                grads = (grads,)
            # pad to the number of primal args (non-tensor args get None→zero)
            out = []
            for a, gr in zip(args, list(grads) + [None] * (len(args) - len(grads))):
                if gr is None:
                    gr = jax.tree_util.tree_map(jnp.zeros_like, a)
                out.append(gr)
            return tuple(out)

        _call.defvjp(_fwd, _bwd)
        cls._impl = _call


class PyLayer(metaclass=_PyLayerMeta):
    """User-defined fwd/bwd (reference autograd/py_layer.py:202).

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x ** 3
        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor
            return 3 * x ** 2 * grad

    y = Cube.apply(x)
    """

    @classmethod
    def apply(cls, *args):
        return cls._impl(*args)

    @staticmethod
    def forward(ctx, *args):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Reference paddle.autograd.backward.  Functional JAX has no global
    tape to walk: gradients come from ``grad``/``value_and_grad``
    transforms over functions.  This surface point exists to fail loudly
    with the migration recipe instead of silently doing nothing."""
    raise RuntimeError(
        "autograd.backward walks a mutable autograd tape, which does not "
        "exist in this functional runtime. Compute gradients with "
        "paddle_tpu.autograd.grad(fn)(params) or jax.value_and_grad over "
        "your loss function (docs/MIGRATION.md: autograd).")
