"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py — RNNCellBase,
SimpleRNNCell/LSTMCell/GRUCell, RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU; the
C++ side is operators/rnn_op + the fused CPU fusion_{gru,lstm} kernels).

TPU-first design:
- time recursion is a single ``lax.scan`` — one compiled loop body, no
  per-step dispatch (the reference's CUDNN-descriptor path collapses into
  XLA's while-loop + fused GEMMs);
- the input projection for ALL timesteps is hoisted out of the scan as one
  big (B*T, in)×(in, G*H) matmul — MXU-shaped — so the scan body only
  carries the (B, H)×(H, G*H) recurrent GEMM;
- gates are computed from a fused 4H/3H-wide projection, paddle's two-bias
  (ih + hh) parameterization kept for state_dict parity;
- variable-length batches mask state updates inside the scan
  (sequence_length semantics of the reference op).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.errors import enforce
from . import functional as F
from .initializer import Uniform
from .layer import Layer, LayerList

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    """Gate-fused single-step cell; ``gates`` = multiplier of hidden width."""

    gates = 1

    def __init__(self, input_size: int, hidden_size: int,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        g = self.gates
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (input_size, g * hidden_size), default_initializer=init,
            attr=weight_ih_attr)
        self.weight_hh = self.create_parameter(
            (hidden_size, g * hidden_size), default_initializer=init,
            attr=weight_hh_attr)
        self.bias_ih = None if bias_ih_attr is False else \
            self.create_parameter((g * hidden_size,), is_bias=True,
                                  default_initializer=init,
                                  attr=bias_ih_attr)
        self.bias_hh = None if bias_hh_attr is False else \
            self.create_parameter((g * hidden_size,), is_bias=True,
                                  default_initializer=init,
                                  attr=bias_hh_attr)

    def project_inputs(self, x):
        """Input-side projection, hoistable across time: x @ W_ih + b_ih."""
        y = x @ self.weight_ih
        if self.bias_ih is not None:
            y = y + self.bias_ih
        return y

    def get_initial_states(self, batch_size: int, dtype=jnp.float32):
        """Zero state; tuple-state cells (LSTM, custom peephole cells…)
        override this — downstream code keys off the returned structure,
        never off the cell's class."""
        return jnp.zeros((batch_size, self.hidden_size), dtype)


class SimpleRNNCell(RNNCellBase):
    """h' = act(x W_ih + b_ih + h W_hh + b_hh) (rnn.py SimpleRNNCell)."""

    gates = 1

    def __init__(self, input_size: int, hidden_size: int,
                 activation: str = "tanh", **kw):
        super().__init__(input_size, hidden_size, **kw)
        enforce(activation in ("tanh", "relu"),
                "SimpleRNNCell activation must be tanh or relu")
        self.activation = activation

    def step(self, xproj, h):
        z = xproj + h @ self.weight_hh
        if self.bias_hh is not None:
            z = z + self.bias_hh
        return jnp.tanh(z) if self.activation == "tanh" else F.relu(z)

    def forward(self, inputs, states=None):
        h = self.get_initial_states(inputs.shape[0], inputs.dtype) \
            if states is None else states
        h = self.step(self.project_inputs(inputs), h)
        return h, h


class LSTMCell(RNNCellBase):
    """i,f,g,o gate order (rnn.py LSTMCell; rnn_op GetGateValue order)."""

    gates = 4

    def get_initial_states(self, batch_size: int, dtype=jnp.float32):
        z = jnp.zeros((batch_size, self.hidden_size), dtype)
        return (z, z)

    def step(self, xproj, state):
        h, c = state
        z = xproj + h @ self.weight_hh
        if self.bias_hh is not None:
            z = z + self.bias_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c = f * c + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return h, c

    def forward(self, inputs, states=None):
        st = self.get_initial_states(inputs.shape[0], inputs.dtype) \
            if states is None else states
        h, c = self.step(self.project_inputs(inputs), st)
        return h, (h, c)


class GRUCell(RNNCellBase):
    """r,z,c gate order with paddle's candidate form
    c = tanh(x W_c + b_c + r*(h W_hc + b_hc)) (rnn.py GRUCell)."""

    gates = 3

    def step(self, xproj, h):
        hproj = h @ self.weight_hh
        if self.bias_hh is not None:
            hproj = hproj + self.bias_hh
        xr, xz, xc = jnp.split(xproj, 3, axis=-1)
        hr, hz, hc = jnp.split(hproj, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        return (1.0 - z) * c + z * h

    def forward(self, inputs, states=None):
        h = self.get_initial_states(inputs.shape[0], inputs.dtype) \
            if states is None else states
        h = self.step(self.project_inputs(inputs), h)
        return h, h


def _scan_layer(cell: RNNCellBase, x_tbf, init_state, seq_lens=None,
                reverse: bool = False):
    """Run one cell over time-major (T, B, F) inputs with lax.scan.

    Variable lengths: a step with t >= seq_len passes the previous state
    through unchanged (output at padded steps is zeros, matching the
    reference op's zero-padded output)."""
    T, B = x_tbf.shape[0], x_tbf.shape[1]
    xproj = cell.project_inputs(x_tbf.reshape(T * B, -1)).reshape(T, B, -1)
    steps = jnp.arange(T)
    if reverse:
        xproj = jnp.flip(xproj, axis=0)
        steps = jnp.flip(steps, axis=0)

    is_tuple = isinstance(init_state, tuple)

    def body(state, inp):
        xp, t = inp
        new_state = cell.step(xp, state)
        h_new = new_state[0] if is_tuple else new_state
        if seq_lens is not None:
            valid = (t < seq_lens)[:, None]
            if is_tuple:       # carry every state leaf through padded steps
                new_state = tuple(jnp.where(valid, n, p)
                                  for n, p in zip(new_state, state))
            else:
                new_state = jnp.where(valid, h_new, state)
            out = jnp.where(valid, h_new, jnp.zeros_like(h_new))
        else:
            out = h_new
        return new_state, out

    final, outs = lax.scan(body, init_state, (xproj, steps))
    if reverse:
        outs = jnp.flip(outs, axis=0)
    return outs, final


class RNN(Layer):
    """Generic scan wrapper over any cell (rnn.py class RNN)."""

    def __init__(self, cell: RNNCellBase, is_reverse: bool = False,
                 time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        init = self.cell.get_initial_states(x.shape[1], x.dtype) \
            if initial_states is None else initial_states
        outs, final = _scan_layer(self.cell, x, init, sequence_length,
                                  self.is_reverse)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final


class BiRNN(Layer):
    """Forward + backward cells, concat outputs (rnn.py class BiRNN)."""

    def __init__(self, cell_fw: RNNCellBase, cell_bw: RNNCellBase,
                 time_major: bool = False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        B = x.shape[1]
        if initial_states is None:
            init_fw = self.cell_fw.get_initial_states(B, x.dtype)
            init_bw = self.cell_bw.get_initial_states(B, x.dtype)
        else:
            init_fw, init_bw = initial_states
        out_fw, fin_fw = _scan_layer(self.cell_fw, x, init_fw,
                                     sequence_length, reverse=False)
        out_bw, fin_bw = _scan_layer(self.cell_bw, x, init_bw,
                                     sequence_length, reverse=True)
        outs = jnp.concatenate([out_fw, out_bw], axis=-1)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, (fin_fw, fin_bw)


class _StackedRNN(Layer):
    """Shared driver for SimpleRNN/LSTM/GRU: num_layers × {forward or
    bidirect} with inter-layer dropout (rnn.py _RNNBase)."""

    cell_cls = SimpleRNNCell

    def __init__(self, input_size: int, hidden_size: int,
                 num_layers: int = 1, direction: str = "forward",
                 time_major: bool = False, dropout: float = 0.0,
                 **cell_kw):
        super().__init__()
        enforce(direction in ("forward", "bidirect", "bidirectional"),
                f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirect = direction != "forward"
        self.time_major = time_major
        self.dropout = dropout
        self.num_directions = 2 if self.bidirect else 1

        cells = []
        for layer_i in range(num_layers):
            in_sz = input_size if layer_i == 0 \
                else hidden_size * self.num_directions
            cells.append(self.cell_cls(in_sz, hidden_size, **cell_kw))
            if self.bidirect:
                cells.append(self.cell_cls(in_sz, hidden_size, **cell_kw))
        self.cells = LayerList(cells)

    def _tuple_state(self) -> bool:
        return isinstance(self.cells[0].get_initial_states(1), tuple)

    def _split_states(self, initial_states, B, dtype):
        """(L*D, B, H) stacked tensors → per-cell states."""
        n = self.num_layers * self.num_directions
        if initial_states is None:
            return [self.cells[i].get_initial_states(B, dtype)
                    for i in range(n)]
        if self._tuple_state():
            h0, c0 = initial_states
            return [(h0[i], c0[i]) for i in range(n)]
        return [initial_states[i] for i in range(n)]

    def _stack_finals(self, finals):
        if isinstance(finals[0], tuple):
            return (jnp.stack([f[0] for f in finals]),
                    jnp.stack([f[1] for f in finals]))
        return jnp.stack(finals)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        states = self._split_states(initial_states, x.shape[1], x.dtype)
        finals = []
        for layer_i in range(self.num_layers):
            if layer_i > 0 and self.dropout > 0:
                x = F.dropout(x, self.dropout, training=self.training)
            ci = layer_i * self.num_directions
            out_fw, fin_fw = _scan_layer(self.cells[ci], x, states[ci],
                                         sequence_length, reverse=False)
            finals.append(fin_fw)
            if self.bidirect:
                out_bw, fin_bw = _scan_layer(self.cells[ci + 1], x,
                                             states[ci + 1],
                                             sequence_length, reverse=True)
                finals.append(fin_bw)
                x = jnp.concatenate([out_fw, out_bw], axis=-1)
            else:
                x = out_fw
        outs = x if self.time_major else jnp.swapaxes(x, 0, 1)
        return outs, self._stack_finals(finals)


class SimpleRNN(_StackedRNN):
    cell_cls = SimpleRNNCell


class LSTM(_StackedRNN):
    cell_cls = LSTMCell


class GRU(_StackedRNN):
    cell_cls = GRUCell
