"""nn layer long tail (reference: python/paddle/nn/layer/{activation,
common,conv,norm,pooling,loss,container}.py + nn/decode.py) — the last
classes of the reference ``nn.__all__`` beyond layers.py, all thin
stateful wrappers over the functional surface.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype
from ..framework.errors import enforce
from . import functional as F
from . import initializer as I
from .layer import Layer
from .layers import _BatchNormBase
from .rnn import RNNCellBase  # noqa: F401  (re-export; reference nn.__all__)

__all__ = [
    "CELU", "ELU", "SELU", "Silu", "Swish", "Softsign", "LogSigmoid",
    "Maxout", "Hardshrink", "Softshrink", "Hardtanh", "ThresholdedReLU",
    "Tanhshrink",
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
    "Dropout2D", "Dropout3D", "AlphaDropout",
    "Unfold", "Fold", "Bilinear",
    "MaxPool3D", "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "Conv1DTranspose", "Conv3DTranspose",
    "BatchNorm", "SyncBatchNorm", "LocalResponseNorm",
    "BCELoss", "HSigmoidLoss",
    "LayerDict", "RNNCellBase", "BeamSearchDecoder", "dynamic_decode",
]


def _act(name, fn, extra=()):
    """Build a stateless activation Layer class around a functional."""
    keys = [k for k, _ in extra]

    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        params = dict(extra)
        if len(args) > len(keys):
            raise TypeError(
                f"{name}() takes at most {len(keys)} positional "
                f"arguments ({len(args)} given)")
        for i, a in enumerate(args):
            params[keys[i]] = a
        for k, v in kwargs.items():
            if k in params:
                params[k] = v
            elif k != "name":
                raise TypeError(f"{name}() got an unexpected keyword "
                                f"argument {k!r}")
        self._extra = [params[k] for k in keys]

    def forward(self, x):
        return fn(x, *self._extra)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward,
                                 "__doc__": f"Stateless {name} activation "
                                            f"(reference nn.{name})."})


CELU = _act("CELU", F.celu, (("alpha", 1.0),))
ELU = _act("ELU", F.elu, (("alpha", 1.0),))
SELU = _act("SELU", F.selu, (("scale", 1.0507009873554805),
                             ("alpha", 1.6732632423543772)))
Silu = _act("Silu", F.silu)
Swish = _act("Swish", F.swish)
Softsign = _act("Softsign", F.softsign)
LogSigmoid = _act("LogSigmoid", F.log_sigmoid)
Hardshrink = _act("Hardshrink", F.hardshrink, (("threshold", 0.5),))
Softshrink = _act("Softshrink", F.softshrink, (("threshold", 0.5),))
Tanhshrink = _act("Tanhshrink", F.tanhshrink)
ThresholdedReLU = _act("ThresholdedReLU", F.thresholded_relu,
                       (("threshold", 1.0),))


class Hardtanh(Layer):
    def __init__(self, min: float = -1.0, max: float = 1.0):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Maxout(Layer):
    def __init__(self, groups: int, axis: int = 1):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


# ---------------------------------------------------------------------------
# Padding (reference nn/layer/common.py PadXD: flat [pre, post] per
# trailing spatial dim, passed through to F.pad's flat convention)
# ---------------------------------------------------------------------------
class _PadND(Layer):
    SPATIAL = 1

    def __init__(self, padding, mode: str = "constant", value: float = 0.0,
                 data_format: Optional[str] = None):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * (2 * self.SPATIAL)
        enforce(len(padding) == 2 * self.SPATIAL,
                f"padding must have {2 * self.SPATIAL} entries")
        self.padding = list(padding)
        self.mode = mode
        self.value = value

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value)


class Pad1D(_PadND):
    SPATIAL = 1


class Pad2D(_PadND):
    SPATIAL = 2


class Pad3D(_PadND):
    SPATIAL = 3


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format: str = "NCHW"):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * 4
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


# ---------------------------------------------------------------------------
# Dropout variants
# ---------------------------------------------------------------------------
class Dropout2D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCHW"):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCDHW"):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


# ---------------------------------------------------------------------------
# Shape ops / bilinear
# ---------------------------------------------------------------------------
class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings,
                     dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Bilinear(Layer):
    def __init__(self, in1_features: int, in2_features: int,
                 out_features: int, weight_attr=None, bias_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_features,), is_bias=True, attr=bias_attr))

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


# ---------------------------------------------------------------------------
# Pooling layers
# ---------------------------------------------------------------------------
class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format: str = "NCDHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format)

    def forward(self, x):
        k, s, p, df = self.args
        return F.max_pool3d(x, k, s, p, data_format=df)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format: str = "NCDHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format)

    def forward(self, x):
        k, s, p, df = self.args
        return F.avg_pool3d(x, k, s, p, data_format=df)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask: bool = False):
        super().__init__()
        enforce(not return_mask,
                "return_mask is unsupported on adaptive max pools here")
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format: str = "NCDHW"):
        super().__init__()
        self.output_size, self.data_format = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask: bool = False,
                 data_format: str = "NCDHW"):
        super().__init__()
        enforce(not return_mask,
                "return_mask is unsupported on adaptive max pools here")
        self.output_size, self.data_format = output_size, data_format

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.data_format)


class _MaxUnPoolND(Layer):
    FN = None

    def __init__(self, kernel_size, stride=None, padding=0,
                 output_size=None, data_format=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self.args
        return type(self).FN(x, indices, k, s, p, o)


class MaxUnPool1D(_MaxUnPoolND):
    FN = staticmethod(F.max_unpool1d)


class MaxUnPool2D(_MaxUnPoolND):
    FN = staticmethod(F.max_unpool2d)


class MaxUnPool3D(_MaxUnPoolND):
    FN = staticmethod(F.max_unpool3d)


# ---------------------------------------------------------------------------
# Transposed convs
# ---------------------------------------------------------------------------
class _ConvTransposeND(Layer):
    ND = 1

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, output_padding=0, groups: int = 1,
                 dilation=1, weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        nd = self.ND
        k = ((kernel_size,) * nd if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *k), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_channels,), is_bias=True, attr=bias_attr))
        self.data_format = data_format or ("NCL" if self.ND == 1
                                           else "NCDHW")
        self.conv_args = (stride, padding, output_padding, groups, dilation)

    def forward(self, x):
        s, p, op, g, d = self.conv_args
        fn = F.conv1d_transpose if self.ND == 1 else F.conv3d_transpose
        return fn(x, self.weight, self.bias, stride=s, padding=p,
                  output_padding=op, groups=g, dilation=d,
                  data_format=self.data_format)


class Conv1DTranspose(_ConvTransposeND):
    ND = 1


class Conv3DTranspose(_ConvTransposeND):
    ND = 3


# ---------------------------------------------------------------------------
# Norm layers
# ---------------------------------------------------------------------------
class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (fluid dygraph BatchNorm signature:
    positional num_channels, optional act)."""

    def __init__(self, num_channels: int, act=None, momentum: float = 0.9,
                 epsilon: float = 1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True, use_global_stats=False,
                 trainable_statistics=False):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr,
                         data_format=data_layout, dtype=dtype)
        self._act = act

    def forward(self, x):
        y = super().forward(x)
        if self._act is not None:
            fn = getattr(F, self._act, None)
            enforce(fn is not None, f"BatchNorm: unknown act {self._act!r}")
            y = fn(y)
        return y


class SyncBatchNorm(_BatchNormBase):
    """Reference SyncBatchNorm (python/paddle/nn/layer/norm.py): batch
    statistics synchronized across data-parallel workers.  Under GSPMD the
    batch axis is sharded over the dp mesh axis and ``jnp.mean`` over it
    compiles to a global reduction (XLA inserts the collective), so the
    plain batch-norm math IS synchronized — no side channel needed.  The
    class exists for the reference surface: `convert_sync_batchnorm`
    rewrites _BatchNormBase instances in a layer tree."""

    @classmethod
    def convert_sync_batchnorm(cls, layer: Layer) -> Layer:
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = cls.__new__(cls)
            out.__dict__.update(layer.__dict__)
            return out
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LocalResponseNorm(Layer):
    def __init__(self, size: int = 5, alpha: float = 1e-4,
                 beta: float = 0.75, k: float = 1.0,
                 data_format: str = "NCHW"):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------
class BCELoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean"):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size: int, num_classes: int,
                 weight_attr=None, bias_attr=None, is_custom: bool = False,
                 is_sparse: bool = False):
        super().__init__()
        enforce(num_classes >= 2, "num_classes must be >= 2")
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_classes - 1,), is_bias=True, attr=bias_attr))

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------
class LayerDict(Layer):
    """Dict container (reference nn.LayerDict): ordered, registers values
    as sublayers."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, sublayer):
        self.add_sublayer(key, sublayer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        v = self._sub_layers[key]
        del self._sub_layers[key]
        return v

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        pairs = sublayers.items() if hasattr(sublayers, "items") \
            else sublayers
        for k, v in pairs:
            self.add_sublayer(k, v)


# ---------------------------------------------------------------------------
# Beam-search decoding (reference nn/decode.py BeamSearchDecoder:64 +
# dynamic_decode:1000)
# ---------------------------------------------------------------------------
class BeamSearchDecoder:
    """Beam search over an RNN cell (reference nn/decode.py:64).

    The cell contract matches paddle: ``cell(inputs, states) -> (out,
    new_states)``; ``output_fn`` maps cell output to vocab logits.  State
    tensors are tiled to (batch * beam, ...).
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size: int):
        """(B, ...) → (B*beam, ...) by repeating each row beam times."""
        x = jnp.asarray(x)
        return jnp.repeat(x, beam_size, axis=0)

    def initialize(self, initial_states, batch_size: int):
        k = self.beam_size
        states = jax.tree_util.tree_map(
            lambda s: self.tile_beam_merge_with_batch(s, k), initial_states)
        tokens = jnp.full((batch_size, k), self.start_token, jnp.int32)
        # beam 0 live, others -inf so the first expansion is from one beam
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (k - 1), jnp.float32)[None, :],
            (batch_size, 1))
        finished = jnp.zeros((batch_size, k), bool)
        return tokens, log_probs, finished, states

    def step(self, tokens, log_probs, finished, states):
        b, k = tokens.shape
        inp = tokens.reshape(b * k)
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        out, new_states = self.cell(inp, states)
        logits = self.output_fn(out) if self.output_fn is not None else out
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, k, v)
        # finished beams only extend with end_token at no cost
        fin_mask = jnp.full((v,), -1e9).at[self.end_token].set(0.0)
        logp = jnp.where(finished[..., None], fin_mask[None, None, :], logp)
        total = log_probs[..., None] + logp            # (B, K, V)
        top_val, top_idx = jax.lax.top_k(total.reshape(b, k * v), k)
        parent = (top_idx // v).astype(jnp.int32)      # (B, K)
        token = (top_idx % v).astype(jnp.int32)
        # reorder states by parent beam
        def reorder(s):
            s = s.reshape(b, k, *s.shape[1:])
            s = jnp.take_along_axis(
                s, parent.reshape(b, k, *([1] * (s.ndim - 2))), axis=1)
            return s.reshape(b * k, *s.shape[2:])
        new_states = jax.tree_util.tree_map(reorder, new_states)
        new_fin = jnp.take_along_axis(finished, parent, axis=1) \
            | (token == self.end_token)
        return token, top_val, new_fin, new_states, parent


def dynamic_decode(decoder, inits=None, max_step_num: int = 32,
                   batch_size: Optional[int] = None, **kwargs):
    """Run a BeamSearchDecoder to completion (reference nn/decode.py:1000):
    returns (token ids (B, beam, T) backtraced via gather_tree, final
    sequence log-probs (B, beam))."""
    enforce(batch_size is not None or inits is not None,
            "dynamic_decode needs inits or batch_size")
    if batch_size is None:
        leaves = jax.tree_util.tree_leaves(inits)
        batch_size = leaves[0].shape[0]
    tokens, log_probs, finished, states = decoder.initialize(
        inits, batch_size)
    ids_steps, parent_steps = [], []
    for _ in range(max_step_num):
        tokens, log_probs, finished, states, parent = decoder.step(
            tokens, log_probs, finished, states)
        ids_steps.append(tokens)
        parent_steps.append(parent)
        if bool(jnp.all(finished)):
            break
    ids = jnp.stack(ids_steps)                 # (T, B, K)
    parents = jnp.stack(parent_steps)
    seqs = F.gather_tree(ids, parents)         # (T, B, K)
    return jnp.transpose(seqs, (1, 2, 0)), log_probs
