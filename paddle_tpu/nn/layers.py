"""Layer classes (reference: python/paddle/nn/layer/*.py).

Thin stateful wrappers over paddle_tpu.nn.functional; parameters follow paddle
shape conventions (Linear weight is (in, out); Conv2D weight is OIHW).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype
from ..framework.errors import enforce
from . import functional as F
from . import initializer as I
from .layer import Layer, LayerList, Parameter, ParameterList, Sequential  # noqa: F401


# ---------------------------------------------------------------------------
# Dense / embedding
# ---------------------------------------------------------------------------
class Linear(Layer):
    """Reference: python/paddle/nn/layer/common.py Linear."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), dtype=dtype, attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), dtype=dtype, is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class Embedding(Layer):
    """Reference: python/paddle/nn/layer/common.py Embedding."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, dtype="float32"):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), dtype=dtype,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
            attr=weight_attr)

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)


# ---------------------------------------------------------------------------
# Conv / pooling
# ---------------------------------------------------------------------------
class Conv2D(Layer):
    """Reference: python/paddle/nn/layer/conv.py Conv2D (NCHW, OIHW)."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 dtype="float32"):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0], k[1]), dtype=dtype,
            default_initializer=I.Uniform(-bound, bound), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), dtype=dtype, is_bias=True,
                default_initializer=I.Uniform(-bound, bound), attr=bias_attr)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------
class LayerNorm(Layer):
    """Reference: python/paddle/nn/layer/norm.py LayerNorm."""

    def __init__(self, normalized_shape, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, dtype=dtype,
                default_initializer=I.Constant(1.0), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, dtype=dtype, is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    def __init__(self, hidden_size: int, epsilon: float = 1e-6, dtype="float32"):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), dtype=dtype, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, weight_attr=None, bias_attr=None,
                 data_format="NCHW", dtype="float32"):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), dtype=dtype,
                default_initializer=I.Constant(1.0), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), dtype=dtype, is_bias=True, attr=bias_attr)
        self.register_buffer("_mean", jnp.zeros((num_features,), convert_dtype(dtype)))
        self.register_buffer("_variance", jnp.ones((num_features,), convert_dtype(dtype)))

    def forward(self, x):
        y, new_mean, new_var = F.batch_norm(
            x, self._buffers["_mean"], self._buffers["_variance"],
            self.weight, self.bias, training=self.training,
            momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format)
        if self.training:
            self._update_buffer("_mean", new_mean)
            self._update_buffer("_variance", new_var)
        return y


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class GroupNorm(Layer):
    def __init__(self, num_groups: int, num_channels: int, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.weight = (None if weight_attr is False else self.create_parameter(
            (num_channels,), dtype=dtype, default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_channels,), dtype=dtype, is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon)


# ---------------------------------------------------------------------------
# Dropout / shaping / activations
# ---------------------------------------------------------------------------
class Dropout(Layer):
    def __init__(self, p: float = 0.5, mode: str = "upscale_in_train"):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return F.flatten(x, self.start_axis, self.stop_axis)


def _act_layer(fn, name):
    class _Act(Layer):
        def __init__(self, *a, **k):
            super().__init__()
            self._a, self._k = a, k

        def forward(self, x):
            return fn(x, *self._a, **self._k)
    _Act.__name__ = name
    return _Act


ReLU = _act_layer(F.relu, "ReLU")
ReLU6 = _act_layer(F.relu6, "ReLU6")
GELU = _act_layer(F.gelu, "GELU")
SiLU = _act_layer(F.silu, "SiLU")
Sigmoid = _act_layer(F.sigmoid, "Sigmoid")
Tanh = _act_layer(F.tanh, "Tanh")
LeakyReLU = _act_layer(F.leaky_relu, "LeakyReLU")
Hardswish = _act_layer(F.hardswish, "Hardswish")
Hardsigmoid = _act_layer(F.hardsigmoid, "Hardsigmoid")
Mish = _act_layer(F.mish, "Mish")
Softplus = _act_layer(F.softplus, "Softplus")
Softmax = _act_layer(F.softmax, "Softmax")
LogSoftmax = _act_layer(F.log_softmax, "LogSoftmax")


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------
class CrossEntropyLoss(Layer):
    def __init__(self, reduction: str = "mean", soft_label: bool = False,
                 ignore_index: int = -100, label_smoothing: float = 0.0):
        super().__init__()
        self.reduction, self.soft_label = reduction, soft_label
        self.ignore_index, self.label_smoothing = ignore_index, label_smoothing

    def forward(self, logits, label):
        return F.cross_entropy(logits, label, soft_label=self.soft_label,
                               reduction=self.reduction,
                               ignore_index=self.ignore_index,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, log_probs, label):
        return F.nll_loss(log_probs, label, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction: str = "mean", delta: float = 1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


# ---------------------------------------------------------------------------
# Transformer family (reference: python/paddle/nn/layer/transformer.py;
# fused Pallas variants live in paddle_tpu/ops/)
# ---------------------------------------------------------------------------
class MultiHeadAttention(Layer):
    """Reference: nn/layer/transformer.py MultiHeadAttention."""

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 kdim=None, vdim=None, need_weights: bool = False,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr, dtype)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr, dtype)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr, dtype)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr, dtype)

    def _split(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split(self.q_proj(query))
        k = self._split(self.k_proj(key))
        v = self._split(self.v_proj(value))
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=2)
            v = jnp.concatenate([cache[1], v], axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out


class TransformerEncoderLayer(Layer):
    """Reference: nn/layer/transformer.py TransformerEncoderLayer; the fused
    semantic twin is reference operators/fused/fused_attention_op.cc +
    fused_feedforward_op.cc."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "relu",
                 attn_dropout=None, act_dropout=None,
                 normalize_before: bool = False, dtype="float32"):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead,
            dropout=attn_dropout if attn_dropout is not None else dropout,
            dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers: int, norm=None):
        super().__init__()
        self.layers = LayerList([encoder_layer_fn() for _ in range(num_layers)])
        self.norm = norm

    def forward(self, src, src_mask=None):
        for layer in self.layers:
            src = layer(src, src_mask=src_mask)
        if self.norm is not None:
            src = self.norm(src)
        return src


class TransformerDecoderLayer(Layer):
    """Reference: nn/layer/transformer.py TransformerDecoderLayer —
    self-attn (causal) + cross-attn + FFN, pre/post-LN switchable."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "relu",
                 attn_dropout=None, act_dropout=None,
                 normalize_before: bool = False, dtype="float32"):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                            dtype=dtype)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                             dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.norm3 = LayerNorm(d_model, dtype=dtype)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, attn_mask=tgt_mask)
        else:
            tgt, new_cache = self.self_attn(tgt, attn_mask=tgt_mask,
                                            cache=cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, new_cache)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer_fn, num_layers: int, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer_fn()
                                 for _ in range(num_layers)])
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                tgt = layer(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)
            else:
                tgt, c = layer(tgt, memory, tgt_mask=tgt_mask,
                               memory_mask=memory_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            tgt = self.norm(tgt)
        return tgt if cache is None else (tgt, new_caches)


class Transformer(Layer):
    """Full encoder-decoder (reference nn/layer/transformer.py Transformer)."""

    def __init__(self, d_model: int = 512, nhead: int = 8,
                 num_encoder_layers: int = 6, num_decoder_layers: int = 6,
                 dim_feedforward: int = 2048, dropout: float = 0.1,
                 activation: str = "relu", normalize_before: bool = False):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        mk_enc = lambda: TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout, activation,
            normalize_before=normalize_before)
        mk_dec = lambda: TransformerDecoderLayer(
            d_model, nhead, dim_feedforward, dropout, activation,
            normalize_before=normalize_before)
        norm = LayerNorm(d_model) if normalize_before else None
        self.encoder = TransformerEncoder(mk_enc, num_encoder_layers,
                                          norm=norm)
        self.decoder = TransformerDecoder(
            mk_dec, num_decoder_layers,
            norm=LayerNorm(d_model) if normalize_before else None)

    @staticmethod
    def generate_square_subsequent_mask(length: int):
        mask = jnp.triu(jnp.full((length, length), float(jnp.finfo(
            jnp.float32).min)), k=1)
        return mask

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)


# ---------------------------------------------------------------------------
# Extended conv/pool/norm/activation layers (reference nn/layer/{conv,
# pooling,norm,activation,vision,distance,loss}.py)
# ---------------------------------------------------------------------------
class Conv1D(Layer):
    def __init__(self, in_channels: int, out_channels: int, kernel_size: int,
                 stride: int = 1, padding: int = 0, dilation: int = 1,
                 groups: int = 1, weight_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__()
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        fan_in = in_channels * kernel_size // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kernel_size), dtype=dtype,
            default_initializer=I.Uniform(-bound, bound), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), dtype=dtype, is_bias=True,
            default_initializer=I.Uniform(-bound, bound), attr=bias_attr)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups)


class Conv3D(Layer):
    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 dtype="float32"):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] * k[2] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *k), dtype=dtype,
            default_initializer=I.Uniform(-bound, bound), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), dtype=dtype, is_bias=True,
            default_initializer=I.Uniform(-bound, bound), attr=bias_attr)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv2DTranspose(Layer):
    """Reference nn/layer/conv.py Conv2DTranspose (IOHW weights)."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, output_padding=0, dilation=1,
                 groups: int = 1, weight_attr=None, bias_attr=None,
                 data_format="NCHW", dtype="float32"):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding = stride, padding
        self.output_padding, self.dilation = output_padding, dilation
        self.groups = groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, k[0], k[1]), dtype=dtype,
            default_initializer=I.Uniform(-bound, bound), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), dtype=dtype, is_bias=True,
            default_initializer=I.Uniform(-bound, bound), attr=bias_attr)

    def forward(self, x, output_size=None):
        out_pad = self.output_padding
        if output_size is not None:
            # derive the extra padding so the output hits output_size
            # exactly: out = (in-1)*s - 2p + d*(k-1) + 1 + out_pad
            s = (self.stride, self.stride) \
                if isinstance(self.stride, int) else tuple(self.stride)
            p = (self.padding, self.padding) \
                if isinstance(self.padding, int) else tuple(self.padding)
            d = (self.dilation, self.dilation) \
                if isinstance(self.dilation, int) else tuple(self.dilation)
            hw = x.shape[2:4] if self.data_format == "NCHW" else x.shape[1:3]
            k = self.weight.shape[2:4]
            out_pad = []
            for i in range(2):
                base = (hw[i] - 1) * s[i] - 2 * p[i] \
                    + d[i] * (k[i] - 1) + 1
                extra = int(output_size[i]) - base
                enforce(0 <= extra < max(s[i], 1),
                        f"output_size[{i}]={output_size[i]} unreachable "
                        f"(base {base}, stride {s[i]})")
                out_pad.append(extra)
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, out_pad,
                                  self.dilation, self.groups,
                                  self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.data_format)


class _InstanceNormBase(Layer):
    """Per-sample, per-channel normalization (reference instance_norm_op)."""

    def __init__(self, num_features: int, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter(
                (num_features,), dtype=dtype,
                default_initializer=I.Constant(1.0), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), dtype=dtype, is_bias=True, attr=bias_attr)

    def forward(self, x):
        x = x.__jax_array__() if hasattr(x, "__jax_array__") else x
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        if self.scale is not None:
            y = y * self.scale.value.reshape(shape)
        if self.bias is not None:
            y = y + self.bias.value.reshape(shape)
        return y


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class SpectralNorm(Layer):
    """Weight spectral normalization via power iteration (reference
    spectral_norm_op; stateful u/v buffers updated in train mode)."""

    def __init__(self, weight_shape, dim: int = 0, power_iters: int = 1,
                 epsilon: float = 1e-12, dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..framework import random as fw_random
        self.register_buffer("weight_u", jax.random.normal(
            fw_random.next_key(), (h,), convert_dtype(dtype)))
        self.register_buffer("weight_v", jax.random.normal(
            fw_random.next_key(), (w,), convert_dtype(dtype)))

    def forward(self, weight):
        weight = weight.__jax_array__() if hasattr(weight, "__jax_array__") \
            else weight
        w = jnp.moveaxis(weight, self.dim, 0).reshape(weight.shape[self.dim],
                                                      -1)
        u, v = self._buffers["weight_u"], self._buffers["weight_v"]
        for _ in range(self.power_iters):
            v = w.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = w @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        if self.training:
            self._update_buffer("weight_u", jax.lax.stop_gradient(u))
            self._update_buffer("weight_v", jax.lax.stop_gradient(v))
        sigma = u @ w @ v
        return weight / sigma


class PReLU(Layer):
    def __init__(self, num_parameters: int = 1, init: float = 0.25,
                 weight_attr=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), dtype=dtype,
            default_initializer=I.Constant(init), attr=weight_attr)

    def forward(self, x):
        return F.prelu(x, self.weight)


class Identity(Layer):
    def forward(self, x):
        return x


class Unflatten(Layer):
    def __init__(self, axis: int, shape):
        super().__init__()
        self.axis, self.shape = axis, tuple(shape)

    def forward(self, x):
        x = x.__jax_array__() if hasattr(x, "__jax_array__") else x
        ax = self.axis % x.ndim
        return x.reshape(x.shape[:ax] + self.shape + x.shape[ax + 1:])


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners: bool = False, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.data_format)


class UpsamplingBilinear2D(Upsample):
    """align_corners=True bilinear — the reference class's semantics."""

    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "bilinear",
                         align_corners=True, data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "nearest", data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor: int, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor: int, data_format="NCHW"):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis: int = 1, eps: float = 1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p: float = 2.0, epsilon: float = 1e-6,
                 keepdim: bool = False):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class GLU(Layer):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class KLDivLoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin: float = 0.0, reduction: str = "mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin: float = 1.0, reduction: str = "mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin: float = 0.0, reduction: str = "mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin: float = 1.0, p: float = 2.0,
                 epsilon: float = 1e-6, swap: bool = False,
                 reduction: str = "mean"):
        super().__init__()
        self.margin, self.p = margin, p
        self.epsilon, self.swap, self.reduction = epsilon, swap, reduction

    def forward(self, anchor, positive, negative):
        return F.triplet_margin_loss(anchor, positive, negative,
                                     self.margin, self.p, self.epsilon,
                                     self.swap, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank: int = 0, reduction: str = "mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction)
