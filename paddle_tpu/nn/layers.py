"""Layer classes (reference: python/paddle/nn/layer/*.py).

Thin stateful wrappers over paddle_tpu.nn.functional; parameters follow paddle
shape conventions (Linear weight is (in, out); Conv2D weight is OIHW).
"""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

from ..framework.dtype import convert_dtype
from . import functional as F
from . import initializer as I
from .layer import Layer, LayerList, Parameter, ParameterList, Sequential  # noqa: F401


# ---------------------------------------------------------------------------
# Dense / embedding
# ---------------------------------------------------------------------------
class Linear(Layer):
    """Reference: python/paddle/nn/layer/common.py Linear."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), dtype=dtype, attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), dtype=dtype, is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class Embedding(Layer):
    """Reference: python/paddle/nn/layer/common.py Embedding."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, dtype="float32"):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), dtype=dtype,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
            attr=weight_attr)

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)


# ---------------------------------------------------------------------------
# Conv / pooling
# ---------------------------------------------------------------------------
class Conv2D(Layer):
    """Reference: python/paddle/nn/layer/conv.py Conv2D (NCHW, OIHW)."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 dtype="float32"):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0], k[1]), dtype=dtype,
            default_initializer=I.Uniform(-bound, bound), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), dtype=dtype, is_bias=True,
                default_initializer=I.Uniform(-bound, bound), attr=bias_attr)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------
class LayerNorm(Layer):
    """Reference: python/paddle/nn/layer/norm.py LayerNorm."""

    def __init__(self, normalized_shape, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, dtype=dtype,
                default_initializer=I.Constant(1.0), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, dtype=dtype, is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    def __init__(self, hidden_size: int, epsilon: float = 1e-6, dtype="float32"):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), dtype=dtype, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, weight_attr=None, bias_attr=None,
                 data_format="NCHW", dtype="float32"):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), dtype=dtype,
                default_initializer=I.Constant(1.0), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), dtype=dtype, is_bias=True, attr=bias_attr)
        self.register_buffer("_mean", jnp.zeros((num_features,), convert_dtype(dtype)))
        self.register_buffer("_variance", jnp.ones((num_features,), convert_dtype(dtype)))

    def forward(self, x):
        y, new_mean, new_var = F.batch_norm(
            x, self._buffers["_mean"], self._buffers["_variance"],
            self.weight, self.bias, training=self.training,
            momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format)
        if self.training:
            self._update_buffer("_mean", new_mean)
            self._update_buffer("_variance", new_var)
        return y


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class GroupNorm(Layer):
    def __init__(self, num_groups: int, num_channels: int, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.weight = (None if weight_attr is False else self.create_parameter(
            (num_channels,), dtype=dtype, default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_channels,), dtype=dtype, is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon)


# ---------------------------------------------------------------------------
# Dropout / shaping / activations
# ---------------------------------------------------------------------------
class Dropout(Layer):
    def __init__(self, p: float = 0.5, mode: str = "upscale_in_train"):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        return F.flatten(x, self.start_axis, self.stop_axis)


def _act_layer(fn, name):
    class _Act(Layer):
        def __init__(self, *a, **k):
            super().__init__()
            self._a, self._k = a, k

        def forward(self, x):
            return fn(x, *self._a, **self._k)
    _Act.__name__ = name
    return _Act


ReLU = _act_layer(F.relu, "ReLU")
ReLU6 = _act_layer(F.relu6, "ReLU6")
GELU = _act_layer(F.gelu, "GELU")
SiLU = _act_layer(F.silu, "SiLU")
Sigmoid = _act_layer(F.sigmoid, "Sigmoid")
Tanh = _act_layer(F.tanh, "Tanh")
LeakyReLU = _act_layer(F.leaky_relu, "LeakyReLU")
Hardswish = _act_layer(F.hardswish, "Hardswish")
Hardsigmoid = _act_layer(F.hardsigmoid, "Hardsigmoid")
Mish = _act_layer(F.mish, "Mish")
Softplus = _act_layer(F.softplus, "Softplus")
Softmax = _act_layer(F.softmax, "Softmax")
LogSoftmax = _act_layer(F.log_softmax, "LogSoftmax")


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------
class CrossEntropyLoss(Layer):
    def __init__(self, reduction: str = "mean", soft_label: bool = False,
                 ignore_index: int = -100, label_smoothing: float = 0.0):
        super().__init__()
        self.reduction, self.soft_label = reduction, soft_label
        self.ignore_index, self.label_smoothing = ignore_index, label_smoothing

    def forward(self, logits, label):
        return F.cross_entropy(logits, label, soft_label=self.soft_label,
                               reduction=self.reduction,
                               ignore_index=self.ignore_index,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, log_probs, label):
        return F.nll_loss(log_probs, label, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction: str = "mean", delta: float = 1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


# ---------------------------------------------------------------------------
# Transformer family (reference: python/paddle/nn/layer/transformer.py;
# fused Pallas variants live in paddle_tpu/ops/)
# ---------------------------------------------------------------------------
class MultiHeadAttention(Layer):
    """Reference: nn/layer/transformer.py MultiHeadAttention."""

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 kdim=None, vdim=None, need_weights: bool = False,
                 weight_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr, dtype)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr, dtype)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr, dtype)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr, dtype)

    def _split(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split(self.q_proj(query))
        k = self._split(self.k_proj(key))
        v = self._split(self.v_proj(value))
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=2)
            v = jnp.concatenate([cache[1], v], axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out


class TransformerEncoderLayer(Layer):
    """Reference: nn/layer/transformer.py TransformerEncoderLayer; the fused
    semantic twin is reference operators/fused/fused_attention_op.cc +
    fused_feedforward_op.cc."""

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int,
                 dropout: float = 0.1, activation: str = "relu",
                 attn_dropout=None, act_dropout=None,
                 normalize_before: bool = False, dtype="float32"):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead,
            dropout=attn_dropout if attn_dropout is not None else dropout,
            dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers: int, norm=None):
        super().__init__()
        self.layers = LayerList([encoder_layer_fn() for _ in range(num_layers)])
        self.norm = norm

    def forward(self, src, src_mask=None):
        for layer in self.layers:
            src = layer(src, src_mask=src_mask)
        if self.norm is not None:
            src = self.norm(src)
        return src
