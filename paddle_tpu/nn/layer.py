"""Layer & Parameter: the module system.

TPU-first design, replacing the reference's dual static/dygraph stacks
(reference: python/paddle/fluid/dygraph/layers.py ``Layer``; parameter storage
fluid/framework.py ``Parameter``).  One codepath, two modes:

- **Eager**: call ``layer(x)`` on concrete arrays; every op executes op-by-op
  (JAX eager).  This is the "dygraph" mode — debugging ergonomics.
- **Compiled**: ``out, new_state = layer.apply(variables, x)`` is a *pure
  function* of a flat variables dict — jit it, grad it, shard it.  This is the
  "static graph" mode; one XLA compilation replaces the reference's entire
  executor/interpreter stack (reference framework/new_executor/
  interpretercore.cc — see SURVEY.md A13 for why no interpreter is built).

Parameters are wrappers over jax.Array implementing ``__jax_array__`` so they
drop into any jnp/lax op unchanged; ``apply`` temporarily rebinds their values
to the caller-provided pytree (tracers under jit), restoring afterwards.
Mutable buffers (BN running stats) updated during ``apply`` are collected and
returned as the updated variables dict.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as fw_random
from ..framework.dtype import convert_dtype
from ..framework.errors import InvalidArgumentError, enforce
from . import initializer as I


class Parameter:
    """A named, trainable tensor. Drops into jnp ops via __jax_array__."""

    __slots__ = ("value", "trainable", "name", "is_bias", "_grad", "pspec")

    def __init__(self, value, trainable: bool = True, name: str = "",
                 is_bias: bool = False):
        self.value = value
        self.trainable = trainable
        self.name = name
        self.is_bias = is_bias
        self._grad = None
        # GSPMD placement: a jax PartitionSpec over the hybrid-mesh axes
        # (set by distributed.mp_layers; None → replicated)
        self.pspec = None

    # -- jax interop ------------------------------------------------------
    def __jax_array__(self):
        return self.value

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    @property
    def size(self):
        return self.value.size

    # paddle parity: stop_gradient is the inverse of trainable
    @property
    def stop_gradient(self):
        return not self.trainable

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.trainable = not v

    @property
    def grad(self):
        return self._grad

    def set_value(self, value):
        self.value = jnp.asarray(value, dtype=self.value.dtype)

    def numpy(self):
        return np.asarray(self.value)

    def astype(self, dtype):
        return self.value.astype(dtype)

    def __repr__(self):
        return (f"Parameter(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.value.dtype}, trainable={self.trainable})")

    # -- arithmetic (delegate to the underlying array) --------------------
    def _v(self, other):
        return other.value if isinstance(other, Parameter) else other

    def __add__(self, o): return self.value + self._v(o)
    def __radd__(self, o): return self._v(o) + self.value
    def __sub__(self, o): return self.value - self._v(o)
    def __rsub__(self, o): return self._v(o) - self.value
    def __mul__(self, o): return self.value * self._v(o)
    def __rmul__(self, o): return self._v(o) * self.value
    def __truediv__(self, o): return self.value / self._v(o)
    def __rtruediv__(self, o): return self._v(o) / self.value
    def __matmul__(self, o): return self.value @ self._v(o)
    def __rmatmul__(self, o): return self._v(o) @ self.value
    def __neg__(self): return -self.value
    def __getitem__(self, idx): return self.value[idx]
    def __array__(self, dtype=None):
        a = np.asarray(self.value)
        return a.astype(dtype) if dtype is not None else a

    @property
    def T(self):
        return self.value.T

    def reshape(self, *shape):
        return self.value.reshape(*shape)


# Thread-local scope used by apply() to collect in-trace buffer mutations.
_scope = threading.local()

# Monotonic hook-handle ids (removal must never free an id for reuse).
_hook_ids = itertools.count()

# full_name() uniquifier per lowercased class name (reference semantics)
_full_name_counts: Dict[str, int] = {}


def _mutation_sink() -> Optional[Dict[str, Any]]:
    return getattr(_scope, "sink", None)


class Layer:
    """Base class for all network modules (reference dygraph layers.py:Layer)."""

    def __init__(self, name_scope: Optional[str] = None):
        # use object.__setattr__ to dodge our own interceptor
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", OrderedDict())
        object.__setattr__(self, "_state_dict_hooks", OrderedDict())

    # -- attribute interception ------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise InvalidArgumentError(
                    "call super().__init__() before assigning parameters")
            params[name] = value
            subs.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            subs[name] = value
            params.pop(name, None) if params else None
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                del params[name]
            if subs is not None and name in subs:
                del subs[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- construction helpers --------------------------------------------
    def create_parameter(self, shape, dtype="float32", default_initializer=None,
                         is_bias: bool = False, trainable: bool = True,
                         attr=None) -> Parameter:
        """Reference: Layer.create_parameter (dygraph layers.py)."""
        dtype = convert_dtype(dtype)
        init = default_initializer
        if init is None and attr is not None and getattr(attr, "initializer", None):
            init = attr.initializer
        if init is None:
            init = I._global_initializer["bias" if is_bias else "weight"]
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        value = init(fw_random.next_key(), tuple(shape), dtype)
        return Parameter(value, trainable=trainable, is_bias=is_bias)

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        self._buffers[name] = jnp.asarray(tensor)
        if not persistable:
            # excluded from state_dict/checkpoints (reference semantics);
            # still visible via named_buffers
            self.__dict__.setdefault("_non_persistable", set()).add(name)
        else:
            self.__dict__.get("_non_persistable", set()).discard(name)
        self.__dict__.pop(name, None)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        self._parameters[name] = parameter
        return parameter

    # -- traversal --------------------------------------------------------
    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix: str = ""
                         ) -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            full = f"{prefix}.{name}" if prefix else name
            if not p.name:
                # lazily assign the dotted path as the parameter's name
                # (paddle auto-names like "linear_0.w_0"); consumed by
                # apply_decay_param_fun / exclude_from_weight_decay_fn
                p.name = full
            yield full, p
        for name, sub in self._sub_layers.items():
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub.named_parameters(prefix=sp)

    def parameters(self):
        return [p for _, p in self.named_parameters()]

    def named_buffers(self, prefix: str = ""):
        for name, b in self._buffers.items():
            yield (f"{prefix}.{name}" if prefix else name), b
        for name, sub in self._sub_layers.items():
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub.named_buffers(prefix=sp)

    def _named_persistable_buffers(self, prefix: str = ""):
        skip = self.__dict__.get("_non_persistable", set())
        for name, b in self._buffers.items():
            if name in skip:
                continue
            yield (f"{prefix}.{name}" if prefix else name), b
        for name, sub in self._sub_layers.items():
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub._named_persistable_buffers(prefix=sp)

    def buffers(self):
        return [b for _, b in self.named_buffers()]

    def children(self):
        """Immediate sublayers (reference Layer.children)."""
        yield from self._sub_layers.values()

    def named_children(self):
        yield from self._sub_layers.items()

    def full_name(self) -> str:
        """Reference Layer.full_name: a unique class-derived name."""
        if not hasattr(self, "_full_name"):
            cls = type(self).__name__.lower()
            n = _full_name_counts.get(cls, 0)
            _full_name_counts[cls] = n + 1
            self._full_name = f"{cls}_{n}"
        return self._full_name

    def extra_repr(self) -> str:
        """Override to add info to repr (reference Layer.extra_repr)."""
        return ""

    def create_variable(self, name=None, persistable=None, dtype="float32"):
        """A non-parameter variable attached to the layer (reference
        Layer.create_variable) — a zero scalar buffer here."""
        var = jnp.zeros((), convert_dtype(dtype))
        key = name or f"_var_{len(self._buffers)}"
        self.register_buffer(key, var, persistable=bool(persistable))
        return self._buffers[key]

    create_tensor = create_variable

    def clear_gradients(self):
        """No-op for API parity: gradients are function outputs here, not
        accumulated state on parameters (docs/MIGRATION.md: autograd)."""

    def backward(self, *args, **kwargs):
        raise RuntimeError(
            "Layer.backward walks a mutable autograd tape, which does not "
            "exist in this functional runtime; use jax.value_and_grad "
            "over a loss function (docs/MIGRATION.md: autograd).")

    def register_state_dict_hook(self, hook):
        """Hook(state_dict) -> state_dict run at every state_dict() call
        on this layer OR any ancestor (reference semantics: sublayer
        hooks fire during the parent's recursion).  Returns a removable
        handle (reference HookRemoveHelper)."""
        hid = next(_hook_ids)
        self._state_dict_hooks[hid] = hook

        class _Handle:
            def remove(_self):
                self._state_dict_hooks.pop(hid, None)

        return _Handle()

    def to_static_state_dict(self, include_buffers: bool = True):
        return self.state_dict(include_buffers=include_buffers)

    # -- state dict -------------------------------------------------------
    def state_dict(self, include_buffers: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = OrderedDict()
        for name, p in self.named_parameters():
            out[name] = p.value
        if include_buffers:
            for name, b in self._named_persistable_buffers():
                out[name] = b
        # run this layer's hooks AND every sublayer's (the reference
        # runs each sublayer's hooks during its recursion)
        for _, sub in self.named_sublayers(include_self=True):
            for hook in getattr(sub, "_state_dict_hooks",
                                OrderedDict()).values():
                result = hook(out)
                if result is not None:
                    out = result
        return out

    def trainable_variables(self) -> Dict[str, Any]:
        return OrderedDict((n, p.value) for n, p in self.named_parameters()
                           if p.trainable)

    def set_state_dict(self, state: Dict[str, Any], strict: bool = True):
        own_params = dict(self.named_parameters())
        buf_owners = {}
        for path, sub in self.named_sublayers(include_self=True):
            for bname in sub._buffers:
                full = f"{path}.{bname}" if path else bname
                buf_owners[full] = (sub, bname)
        # the state_dict exclusion rule, from its single source of truth
        persistable_names = {n for n, _ in self._named_persistable_buffers()}
        unexpected = []
        for name, value in state.items():
            if name in own_params:
                p = own_params[name]
                enforce(tuple(value.shape) == p.shape,
                        f"shape mismatch for {name}: {tuple(value.shape)} vs {p.shape}")
                p.value = jnp.asarray(value, dtype=p.value.dtype)
            elif name in buf_owners:
                sub, bname = buf_owners[name]
                sub._buffers[bname] = jnp.asarray(value)
            else:
                unexpected.append(name)
        if strict:
            # non-persistable buffers are excluded from state_dict, so a
            # strict round-trip must not demand them back
            missing = [k for k in list(own_params)
                       + [b for b in buf_owners if b in persistable_names]
                       if k not in state]
            if unexpected or missing:
                raise KeyError(
                    f"state_dict mismatch: unexpected={unexpected}, "
                    f"missing={missing}")
        return self

    load_dict = set_state_dict

    # -- train / eval -----------------------------------------------------
    def train(self):
        object.__setattr__(self, "training", True)
        for sub in self._sub_layers.values():
            sub.train()
        return self

    def eval(self):
        object.__setattr__(self, "training", False)
        for sub in self._sub_layers.values():
            sub.eval()
        return self

    def apply_fn(self, fn: Callable[["Layer"], None]):
        """Apply ``fn`` to self and every sublayer (paddle Layer.apply)."""
        for sub in self._sub_layers.values():
            sub.apply_fn(fn)
        fn(self)
        return self

    def astype(self, dtype):
        """Cast all parameters/buffers in place (paddle Layer.to(dtype))."""
        dtype = convert_dtype(dtype)
        for _, p in self.named_parameters():
            if jnp.issubdtype(p.value.dtype, jnp.floating):
                p.value = p.value.astype(dtype)
        for path, sub in self.named_sublayers(include_self=True):
            for bname, b in list(sub._buffers.items()):
                if jnp.issubdtype(b.dtype, jnp.floating):
                    sub._buffers[bname] = b.astype(dtype)
        return self

    to = astype

    # -- hooks ------------------------------------------------------------
    def register_forward_post_hook(self, hook):
        handle = next(_hook_ids)   # never reused, even after removals
        self._forward_post_hooks[handle] = hook
        return handle

    def register_forward_pre_hook(self, hook):
        handle = next(_hook_ids)
        self._forward_pre_hooks[handle] = hook
        return handle

    # -- call -------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            r = hook(self, args)
            if r is not None:
                args = r
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            r = hook(self, args, out)
            if r is not None:
                out = r
        return out

    # -- buffer mutation (jit-safe) ---------------------------------------
    def _update_buffer(self, name: str, value, full_name_hint: str = ""):
        """Update a buffer such that apply() can observe it. In eager mode it
        mutates in place; inside apply() the new (traced) value is recorded in
        the mutation sink and returned from apply()."""
        self._buffers[name] = value
        sink = _mutation_sink()
        if sink is not None:
            sink[(id(self), name)] = value

    # -- functional path --------------------------------------------------
    @contextlib.contextmanager
    def bind(self, variables: Dict[str, Any]):
        """Temporarily substitute parameter/buffer values from a flat dict."""
        own_params = dict(self.named_parameters())
        buf_owners = {}
        for path, sub in self.named_sublayers(include_self=True):
            for bname in sub._buffers:
                full = f"{path}.{bname}" if path else bname
                buf_owners[full] = (sub, bname)
        saved_p, saved_b = {}, {}
        try:
            for name, value in variables.items():
                if name in own_params:
                    saved_p[name] = own_params[name].value
                    own_params[name].value = value
                elif name in buf_owners:
                    sub, bname = buf_owners[name]
                    saved_b[name] = sub._buffers[bname]
                    sub._buffers[bname] = value
                # silently ignore extras (e.g. optimizer slots)
            yield
        finally:
            for name, value in saved_p.items():
                own_params[name].value = value
            for name, (sub, bname) in buf_owners.items():
                if name in saved_b:
                    sub._buffers[bname] = saved_b[name]

    def apply(self, variables: Dict[str, Any], *args, mutable: bool = False,
              method: Optional[str] = None, **kwargs):
        """Pure-function forward: ``out = layer.apply(vars, *args)``.

        With ``mutable=True`` returns ``(out, new_variables)`` where
        new_variables contains updated buffer values (BN running stats etc.).
        ``method`` names an alternative entry point (e.g. a layer's
        ``forward_with_aux``) to call instead of ``forward``.
        Safe under jax.jit / grad / shard_map.
        """
        prev_sink = _mutation_sink()
        _scope.sink = {} if mutable else None
        try:
            with self.bind(variables):
                if method is None:
                    out = self(*args, **kwargs)
                else:
                    out = getattr(self, method)(*args, **kwargs)
                if not mutable:
                    return out
                # map (layer id, buffer name) -> full path
                id_to_path = {}
                for path, sub in self.named_sublayers(include_self=True):
                    for bname in sub._buffers:
                        full = f"{path}.{bname}" if path else bname
                        id_to_path[(id(sub), bname)] = full
                new_vars = dict(variables)
                for key, value in _scope.sink.items():
                    if key in id_to_path:
                        new_vars[id_to_path[key]] = value
                return out, new_vars
        finally:
            _scope.sink = prev_sink

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else type(self).__name__ + "()"


class Sequential(Layer):
    """Reference: paddle.nn.Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    """Reference: paddle.nn.LayerList."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, layer: Layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, p: Parameter):
        self.add_parameter(str(len(self._parameters)), p)
        return self

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())
