"""nn.functional long-tail ops (reference: python/paddle/nn/functional/
{activation,common,conv,loss,norm,pooling,vision,extension}.py) — the last
names of the reference functional ``__all__`` beyond the core set in
``functional.py``.

Same design stance as ``functional.py``: thin, paddle-shaped adapters over
jnp/lax — XLA owns the kernels; anything that is a windowed reduction rides
``reduce_window``/``conv_general_dilated_patches``, anything dense rides
einsum/matmul so it tiles onto the MXU.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import random as fw_random
from ..framework.errors import enforce

__all__ = [
    # activations
    "celu", "elu_", "hardshrink", "hardtanh", "log_sigmoid", "maxout",
    "relu_", "selu", "softmax_", "softshrink", "softsign", "tanh_",
    "tanhshrink", "thresholded_relu", "gumbel_softmax",
    # conv
    "conv1d_transpose", "conv3d_transpose",
    # common / extension
    "diag_embed", "sequence_mask", "dropout2d", "dropout3d",
    "alpha_dropout", "zeropad2d", "unfold", "fold", "upsample", "bilinear",
    "temporal_shift",
    # pooling
    "avg_pool3d", "max_pool3d", "max_unpool1d", "max_unpool2d",
    "max_unpool3d", "adaptive_avg_pool1d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool3d",
    # losses
    "binary_cross_entropy", "dice_loss", "hsigmoid_loss", "log_loss",
    "npair_loss", "sigmoid_focal_loss", "softmax_with_cross_entropy",
    "margin_cross_entropy", "class_center_sample",
    # norm
    "local_response_norm", "instance_norm",
    # vision
    "affine_grid", "grid_sample",
    # decoding
    "gather_tree",
]


def _arr(x):
    return x if isinstance(x, jax.Array) else jnp.asarray(x)


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        enforce(len(v) == n, f"expected {n} values, got {v}")
        return tuple(int(i) for i in v)
    return (int(v),) * n


# ---------------------------------------------------------------------------
# Activations (reference nn/functional/activation.py)
# ---------------------------------------------------------------------------
def celu(x, alpha: float = 1.0):
    x = _arr(x)
    enforce(alpha != 0, "celu alpha must be non-zero")
    return jnp.maximum(x, 0) + jnp.minimum(
        alpha * jnp.expm1(x / alpha), 0).astype(x.dtype)


def selu(x, scale: float = 1.0507009873554805,
         alpha: float = 1.6732632423543772):
    x = _arr(x)
    return (scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))).astype(x.dtype)


def softsign(x):
    x = _arr(x)
    return x / (1 + jnp.abs(x))


def softshrink(x, threshold: float = 0.5):
    x = _arr(x)
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold,
                               jnp.zeros((), x.dtype)))


def hardshrink(x, threshold: float = 0.5):
    x = _arr(x)
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros((), x.dtype))


def hardtanh(x, min: float = -1.0, max: float = 1.0):  # noqa: A002
    return jnp.clip(_arr(x), min, max)


def tanhshrink(x):
    x = _arr(x)
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold: float = 1.0):
    x = _arr(x)
    return jnp.where(x > threshold, x, jnp.zeros((), x.dtype))


def log_sigmoid(x):
    return jax.nn.log_sigmoid(_arr(x))


def maxout(x, groups: int, axis: int = 1):
    """Max over ``groups`` consecutive channel slices (maxout op)."""
    x = _arr(x)
    if axis < 0:
        axis += x.ndim
    c = x.shape[axis]
    enforce(c % groups == 0,
            f"maxout: channels {c} not divisible by groups {groups}")
    shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(shape), axis=axis + 1)


def gumbel_softmax(x, temperature: float = 1.0, hard: bool = False,
                   axis: int = -1, key=None):
    """Gumbel-softmax sampling with optional straight-through hard mode."""
    x = _arr(x)
    if key is None:
        key = fw_random.op_key()
    u = jax.random.uniform(key, x.shape, jnp.float32, 1e-20, 1.0)
    g = -jnp.log(-jnp.log(u))
    y = jax.nn.softmax((x.astype(jnp.float32) + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y).at[...].set(0)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis,
                                    inplace=False)
        y = onehot + y - lax.stop_gradient(y)   # straight-through
    return y.astype(x.dtype)


# documented in-place aliases — arrays are immutable, result is returned
def relu_(x):
    return jax.nn.relu(_arr(x))


def elu_(x, alpha: float = 1.0):
    from . import functional as F
    return F.elu(x, alpha)


def tanh_(x):
    return jnp.tanh(_arr(x))


def softmax_(x, axis: int = -1):
    return jax.nn.softmax(_arr(x), axis=axis)


# ---------------------------------------------------------------------------
# Transposed convs (reference conv2d_transpose generalized; same padding
# arithmetic: out = (in-1)*s - 2*p + d*(k-1) + 1 + output_padding)
# ---------------------------------------------------------------------------
def _convnd_transpose(x, weight, bias, stride, padding, output_padding,
                      dilation, groups, nd, channel_last):
    from ..amp import state as amp_state
    x, weight = amp_state.cast_for_op("conv2d", _arr(x), _arr(weight))
    s = _ntuple(stride, nd)
    d = _ntuple(dilation, nd)
    p = _ntuple(padding, nd)
    op = _ntuple(output_padding, nd)
    ksp = [(weight.shape[2 + i] - 1) * d[i] + 1 for i in range(nd)]
    pad = [(ksp[i] - 1 - p[i], ksp[i] - 1 - p[i] + op[i]) for i in range(nd)]
    spat = "DHW"[3 - nd:]
    fmt = ("N" + spat + "C") if channel_last else ("NC" + spat)
    dn = lax.conv_dimension_numbers(
        x.shape, (weight.shape[1] * groups, weight.shape[0] // groups,
                  *weight.shape[2:]),
        (fmt, "OI" + spat, fmt))
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))  # (in, out/g, *k)
    in_g = weight.shape[0] // groups
    w = w.reshape(groups, in_g, weight.shape[1], *weight.shape[2:])
    w = jnp.swapaxes(w, 1, 2)
    w = w.reshape(groups * weight.shape[1], in_g, *weight.shape[2:])
    y = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pad, lhs_dilation=s,
        rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        b = _arr(bias).astype(y.dtype)
        shape = ((1,) * (y.ndim - 1) + (-1,)) if channel_last \
            else ((1, -1) + (1,) * nd)
        y = y + b.reshape(shape)
    return y


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups: int = 1, dilation=1,
                     data_format: str = "NCL"):
    """(N, C, L) transposed conv; weight (in, out/g, k)."""
    cl = data_format == "NLC"
    x2 = _arr(x)[:, :, None, :] if not cl else _arr(x)[:, None, :, :]
    w2 = _arr(weight)[:, :, None, :]
    y = _convnd_transpose(x2, w2, bias, (1, _ntuple(stride, 1)[0]),
                          (0, _ntuple(padding, 1)[0]),
                          (0, _ntuple(output_padding, 1)[0]),
                          (1, _ntuple(dilation, 1)[0]), groups, 2, cl)
    return y[:, :, 0, :] if not cl else y[:, 0, :, :]


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups: int = 1, dilation=1,
                     data_format: str = "NCDHW"):
    """(N, C, D, H, W) transposed conv; weight (in, out/g, kd, kh, kw)."""
    return _convnd_transpose(x, weight, bias, stride, padding,
                             output_padding, dilation, groups, 3,
                             data_format == "NDHWC")


# ---------------------------------------------------------------------------
# Common / extension (reference nn/functional/{common,extension}.py)
# ---------------------------------------------------------------------------
def diag_embed(input, offset: int = 0, dim1: int = -2, dim2: int = -1):  # noqa: A002
    """Embed the last dim as (offset) diagonals of new square matrices."""
    x = _arr(input)
    n = x.shape[-1] + abs(offset)
    rows = jnp.arange(x.shape[-1]) + max(-offset, 0)
    cols = jnp.arange(x.shape[-1]) + max(offset, 0)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    out = out.at[..., rows, cols].set(x)
    # move the two new dims to (dim1, dim2)
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for dst, src in order:
            perm.insert(dst, src)
        out = jnp.transpose(out, perm)
    return out


def sequence_mask(x, maxlen: Optional[int] = None, dtype="int64"):
    """(..., maxlen) mask of position < length (reference sequence_mask)."""
    from ..framework.dtype import convert_dtype
    x = _arr(x)
    if maxlen is None:
        maxlen = int(jnp.max(x))  # eager only; pass maxlen under jit
    pos = jnp.arange(maxlen)
    return (pos < x[..., None]).astype(convert_dtype(dtype))


def _dropout_channels(x, p, training, ndim_spatial, key=None):
    x = _arr(x)
    enforce(x.ndim == 2 + ndim_spatial,
            f"expected {2 + ndim_spatial}-D input, got {x.ndim}-D")
    if not training or p == 0.0:
        return x
    if key is None:
        key = fw_random.op_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape[:2])
    keep = keep.reshape(keep.shape + (1,) * ndim_spatial)
    return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype)).astype(
        x.dtype)


def dropout2d(x, p: float = 0.5, training: bool = True,
              data_format: str = "NCHW", key=None):
    """Drop whole channels of a 4-D tensor (reference dropout2d)."""
    enforce(data_format == "NCHW", "dropout2d supports NCHW")
    return _dropout_channels(x, p, training, 2, key)


def dropout3d(x, p: float = 0.5, training: bool = True,
              data_format: str = "NCDHW", key=None):
    enforce(data_format == "NCDHW", "dropout3d supports NCDHW")
    return _dropout_channels(x, p, training, 3, key)


def alpha_dropout(x, p: float = 0.5, training: bool = True, key=None):
    """SELU-preserving dropout (reference alpha_dropout): dropped units go
    to -alpha' and the output is affinely corrected to keep (0, 1) stats."""
    x = _arr(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772 * 1.0507009873554805
    neg = -alpha
    a = (1 - p + p * neg ** 2) ** -0.5
    b = -a * p * neg
    if key is None:
        key = fw_random.op_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    return (a * jnp.where(keep, x, jnp.asarray(neg, x.dtype)) + b).astype(
        x.dtype)


def zeropad2d(x, padding, data_format: str = "NCHW"):
    l, r, t, b = _ntuple(padding, 4)
    cfg = ((0, 0), (0, 0), (t, b), (l, r)) if data_format == "NCHW" \
        else ((0, 0), (t, b), (l, r), (0, 0))
    return jnp.pad(_arr(x), cfg)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference unfold op): (N, C, H, W) → (N, C*kh*kw, L)."""
    x = _arr(x)
    k = _ntuple(kernel_sizes, 2)
    s = _ntuple(strides, 2)
    p = _ntuple(paddings, 2)
    d = _ntuple(dilations, 2)
    patches = lax.conv_general_dilated_patches(
        x, k, s, [(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, x.shape[1], *k), ("NCHW", "OIHW", "NCHW")))
    # patches: (N, C*kh*kw, oh, ow)
    return patches.reshape(patches.shape[0], patches.shape[1], -1)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im, the scatter-add inverse of unfold (reference fold op)."""
    x = _arr(x)
    oh, ow = _ntuple(output_sizes, 2)
    kh, kw = _ntuple(kernel_sizes, 2)
    s = _ntuple(strides, 2)
    p = _ntuple(paddings, 2)
    d = _ntuple(dilations, 2)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    nw = (ow + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    enforce(nh * nw == L,
            f"fold: {L} columns inconsistent with output {oh}x{ow}")
    cols = x.reshape(n, c, kh, kw, nh, nw)
    # target row/col for each (kh, nh) / (kw, nw) pair, in padded coords
    ph = oh + 2 * p[0]
    pw = ow + 2 * p[1]
    out = jnp.zeros((n, c, ph, pw), x.dtype)
    rows = (np.arange(kh)[:, None] * d[0]
            + np.arange(nh)[None, :] * s[0]).reshape(-1)
    colsi = (np.arange(kw)[:, None] * d[1]
             + np.arange(nw)[None, :] * s[1]).reshape(-1)
    src = cols.transpose(0, 1, 2, 4, 3, 5).reshape(n, c, kh * nh, kw * nw)
    out = out.at[:, :, rows[:, None], colsi[None, :]].add(src)
    return out[:, :, p[0]:ph - p[0] if p[0] else ph,
               p[1]:pw - p[1] if p[1] else pw]


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW"):
    from . import functional as F
    return F.interpolate(x, size=size, scale_factor=scale_factor,
                         mode=mode, align_corners=align_corners,
                         data_format=data_format)


def bilinear(x1, x2, weight, bias=None):
    """out[n, o] = x1[n] @ W[o] @ x2[n] (reference bilinear op);
    weight (out, in1, in2)."""
    x1, x2, weight = _arr(x1), _arr(x2), _arr(weight)
    y = jnp.einsum("ni,oij,nj->no", x1, weight, x2)
    if bias is not None:
        y = y + _arr(bias).reshape(1, -1)
    return y


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25,
                   data_format: str = "NCHW"):
    """TSM channel shift along the segment (time) axis (reference
    temporal_shift_op): the first ``shift_ratio`` of channels shift
    backward in time, the next ``shift_ratio`` forward, rest stay."""
    enforce(data_format == "NCHW", "temporal_shift supports NCHW")
    x = _arr(x)
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.concatenate(
        [xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([back, fwd, xr[:, :, c2:]], axis=2)
    return out.reshape(nt, c, h, w)


# ---------------------------------------------------------------------------
# Pooling (reference nn/functional/pooling.py) — N-D generalizations
# ---------------------------------------------------------------------------
def _pool_nd(x, kernel, stride, padding, nd, reducer, init, channel_last):
    k = _ntuple(kernel, nd)
    s = _ntuple(stride if stride is not None else kernel, nd)
    p = _ntuple(padding, nd)
    if channel_last:
        window = (1, *k, 1)
        strides = (1, *s, 1)
        pads = ((0, 0), *[(i, i) for i in p], (0, 0))
    else:
        window = (1, 1, *k)
        strides = (1, 1, *s)
        pads = ((0, 0), (0, 0), *[(i, i) for i in p])
    return lax.reduce_window(x, init, reducer, window, strides, pads)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format: str = "NCDHW"):
    x = _arr(x)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    return _pool_nd(x, kernel_size, stride, padding, 3, lax.max, init,
                    data_format == "NDHWC")


def avg_pool3d(x, kernel_size, stride=None, padding=0,
               data_format: str = "NCDHW"):
    x = _arr(x)
    cl = data_format == "NDHWC"
    summed = _pool_nd(x, kernel_size, stride, padding, 3, lax.add, 0.0, cl)
    counts = _pool_nd(jnp.ones_like(x), kernel_size, stride, padding, 3,
                      lax.add, 0.0, cl)
    return summed / counts


def _adaptive_pool_axis(x, axis, out_size, op):
    """Adaptive pool one axis via trace-time bin edges (shared with the
    2-D adaptive pools in functional.py)."""
    from .functional import _adaptive_avg_matrix, _adaptive_bins
    in_size = x.shape[axis]
    if op == "avg":
        m = jnp.asarray(_adaptive_avg_matrix(in_size, out_size), x.dtype)
        return jnp.moveaxis(
            jnp.tensordot(jnp.moveaxis(x, axis, -1), m, axes=[[-1], [1]]),
            -1, axis)
    idx, mask = _adaptive_bins(in_size, out_size)
    xm = jnp.moveaxis(x, axis, -1)
    g = xm[..., jnp.asarray(idx)]                    # (..., out, span)
    neg = jnp.asarray(jnp.finfo(x.dtype).min
                      if jnp.issubdtype(x.dtype, jnp.floating)
                      else jnp.iinfo(x.dtype).min, x.dtype)
    g = jnp.where(jnp.asarray(mask), g, neg)
    return jnp.moveaxis(g.max(axis=-1), -1, axis)


def adaptive_avg_pool1d(x, output_size):
    """(N, C, L) → (N, C, output_size)."""
    return _adaptive_pool_axis(_arr(x), 2, int(output_size), "avg")


def adaptive_max_pool1d(x, output_size, return_mask: bool = False):
    enforce(not return_mask, "return_mask unsupported on adaptive 1d")
    return _adaptive_pool_axis(_arr(x), 2, int(output_size), "max")


def adaptive_avg_pool3d(x, output_size, data_format: str = "NCDHW"):
    enforce(data_format == "NCDHW", "adaptive_avg_pool3d supports NCDHW")
    x = _arr(x)
    od, oh, ow = _ntuple(output_size, 3)
    for axis, o in ((2, od), (3, oh), (4, ow)):
        x = _adaptive_pool_axis(x, axis, o, "avg")
    return x


def adaptive_max_pool3d(x, output_size, data_format: str = "NCDHW"):
    enforce(data_format == "NCDHW", "adaptive_max_pool3d supports NCDHW")
    x = _arr(x)
    od, oh, ow = _ntuple(output_size, 3)
    for axis, o in ((2, od), (3, oh), (4, ow)):
        x = _adaptive_pool_axis(x, axis, o, "max")
    return x


# --- max-unpool family: scatter values back to argmax positions ----------
def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size):
    x, indices = _arr(x), _arr(indices)
    k = _ntuple(kernel_size, nd)
    s = _ntuple(stride if stride is not None else kernel_size, nd)
    p = _ntuple(padding, nd)
    n, c = x.shape[0], x.shape[1]
    in_sp = x.shape[2:]
    if output_size is None:
        out_sp = tuple((in_sp[i] - 1) * s[i] - 2 * p[i] + k[i]
                       for i in range(nd))
    else:
        out_sp = _ntuple(output_size, nd)
    flat = int(np.prod(out_sp))
    xf = x.reshape(n, c, -1)
    idxf = indices.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, flat), x.dtype).at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], idxf
    ].set(xf)
    return out.reshape(n, c, *out_sp)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format: str = "NCL"):
    enforce(data_format == "NCL", "max_unpool1d supports NCL")
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format: str = "NCHW"):
    """Scatter pooled values to their argmax positions (reference
    max_unpool2d; ``indices`` as returned by max_pool2d(return_mask=True),
    flattened over the output plane)."""
    enforce(data_format == "NCHW", "max_unpool2d supports NCHW")
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format: str = "NCDHW"):
    enforce(data_format == "NCDHW", "max_unpool3d supports NCDHW")
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size)


# ---------------------------------------------------------------------------
# Losses (reference nn/functional/loss.py)
# ---------------------------------------------------------------------------
def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    enforce(reduction == "none", f"unknown reduction {reduction!r}")
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    """BCE on probabilities (reference binary_cross_entropy; see also
    F.binary_cross_entropy_with_logits for the logits form)."""
    x = _arr(input).astype(jnp.float32)
    y = _arr(label).astype(jnp.float32)
    eps = 1e-12
    loss = -(y * jnp.log(jnp.maximum(x, eps))
             + (1 - y) * jnp.log(jnp.maximum(1 - x, eps)))
    if weight is not None:
        loss = loss * _arr(weight)
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon: float = 1e-5):  # noqa: A002
    """1 - dice coefficient over the last dim's class probs (reference
    dice_loss): label holds class ids with a trailing singleton dim."""
    x = _arr(input)
    y = _arr(label)
    if y.shape[-1] == 1:
        y = y[..., 0]
    oh = jax.nn.one_hot(y, x.shape[-1], dtype=x.dtype)
    red = tuple(range(1, x.ndim))
    inter = jnp.sum(x * oh, axis=red)
    union = jnp.sum(x, axis=red) + jnp.sum(oh, axis=red)
    return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))


def log_loss(input, label, epsilon: float = 1e-4):  # noqa: A002
    x = _arr(input).astype(jnp.float32)
    y = _arr(label).astype(jnp.float32)
    return -(y * jnp.log(x + epsilon)
             + (1 - y) * jnp.log(1 - x + epsilon))


def npair_loss(anchor, positive, labels, l2_reg: float = 0.002):
    """N-pair loss (reference npair_loss): cross-entropy over the
    anchor·positiveᵀ similarity with same-label targets + L2 on embeds."""
    a = _arr(anchor).astype(jnp.float32)
    p = _arr(positive).astype(jnp.float32)
    y = _arr(labels).reshape(-1)
    sim = a @ p.T                                   # (B, B)
    tgt = (y[:, None] == y[None, :]).astype(jnp.float32)
    tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
    ce = -jnp.mean(jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                    + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
    return ce + reg


def sigmoid_focal_loss(logit, label, normalizer=None, alpha: float = 0.25,
                       gamma: float = 2.0, reduction: str = "sum"):
    """Focal loss on logits (reference sigmoid_focal_loss)."""
    x = _arr(logit).astype(jnp.float32)
    y = _arr(label).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / _arr(normalizer)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label: bool = False,
                               ignore_index: int = -100,
                               numeric_stable_mode: bool = True,
                               return_softmax: bool = False, axis: int = -1):
    """The fused op the reference trains with (softmax_with_cross_entropy):
    per-sample loss keeping the class axis as a singleton; optionally the
    softmax too."""
    x = _arr(logits)
    y = _arr(label)
    lsm = jax.nn.log_softmax(x.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(y.astype(jnp.float32) * lsm, axis=axis,
                        keepdims=True)
    else:
        yi = y if y.ndim == x.ndim else jnp.expand_dims(y, axis)
        safe = jnp.where(yi == ignore_index, 0, yi)
        nll = -jnp.take_along_axis(lsm, safe.astype(jnp.int32), axis=axis)
        loss = jnp.where(yi == ignore_index, 0.0, nll)
    if return_softmax:
        return loss, jax.nn.softmax(x.astype(jnp.float32), axis=axis)
    return loss


def margin_cross_entropy(logits, label, margin1: float = 1.0,
                         margin2: float = 0.5, margin3: float = 0.0,
                         scale: float = 64.0, group=None,
                         return_softmax: bool = False,
                         reduction: Optional[str] = "mean"):
    """ArcFace/CosFace-family margin softmax (reference
    margin_cross_entropy): logits are cosines; the target class cosine
    becomes cos(m1·θ + m2) - m3 before scaling."""
    x = _arr(logits).astype(jnp.float32)
    y = _arr(label).reshape(-1)
    cos_t = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), axis=1)
    theta = jnp.arccos(jnp.clip(cos_t, -1.0 + 1e-7, 1.0 - 1e-7))
    target = jnp.cos(margin1 * theta + margin2) - margin3
    oh = jax.nn.one_hot(y, x.shape[1], dtype=x.dtype)
    adj = x + oh * (target - cos_t)
    adj = adj * scale
    lsm = jax.nn.log_softmax(adj, axis=1)
    loss = -jnp.take_along_axis(lsm, y[:, None].astype(jnp.int32), axis=1)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jax.nn.softmax(adj, axis=1)
    return loss


def class_center_sample(label, num_classes: int, num_samples: int,
                        group=None, seed: Optional[int] = None):
    """Sample class centers: positives plus random negatives up to
    ``num_samples`` (reference class_center_sample, the PartialFC
    primitive).  Host-side sampling (numpy): the op prepares training
    metadata, not traced compute."""
    y = np.asarray(label).reshape(-1)
    rng = np.random.RandomState(seed if seed is not None
                                else np.random.randint(2 ** 31))
    pos = np.unique(y)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos,
                            assume_unique=False)
        rng.shuffle(rest)
        sampled = np.concatenate([pos, rest[:num_samples - len(pos)]])
    sampled = np.sort(sampled)
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (jnp.asarray(remap[y]), jnp.asarray(sampled))


def hsigmoid_loss(input, label, num_classes: int, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse: bool = False):
    """Hierarchical sigmoid over the default complete binary tree
    (reference hsigmoid_loss / hierarchical_sigmoid_op): word2vec-style
    heap layout — leaf ``l`` sits at heap position ``l + num_classes``;
    internal node ``k``'s parameters are row ``k - 1`` of ``weight``
    ((num_classes - 1, feature)).  Custom trees ride path_table/path_code
    ((B, L) node ids / branch codes, -1 padded)."""
    x = _arr(input).astype(jnp.float32)
    y = np.asarray(label).reshape(-1) if path_table is None else None
    w = _arr(weight).astype(jnp.float32)
    if path_table is None:
        depth = int(np.ceil(np.log2(num_classes))) + 1
        tables, codes = [], []
        for l in y:
            node = int(l) + num_classes
            t, c = [], []
            while node > 1:
                t.append(node // 2 - 1)     # internal node row
                c.append(node % 2)          # branch taken
                node //= 2
            t += [-1] * (depth - len(t))
            c += [0] * (depth - len(c))
            tables.append(t[:depth])
            codes.append(c[:depth])
        path_table = jnp.asarray(tables, jnp.int32)
        path_code = jnp.asarray(codes, jnp.float32)
    else:
        path_table = _arr(path_table).astype(jnp.int32)
        path_code = _arr(path_code).astype(jnp.float32)
    valid = path_table >= 0
    safe = jnp.where(valid, path_table, 0)
    wn = w[safe]                                    # (B, L, F)
    z = jnp.einsum("bf,blf->bl", x, wn)
    if bias is not None:
        z = z + _arr(bias).astype(jnp.float32).reshape(-1)[safe]
    # code 1 → sigmoid(z), code 0 → sigmoid(-z)
    sign = 2.0 * path_code - 1.0
    ll = jax.nn.log_sigmoid(sign * z)
    loss = -jnp.sum(jnp.where(valid, ll, 0.0), axis=1)
    return loss[:, None]


# ---------------------------------------------------------------------------
# Norm (reference nn/functional/norm.py)
# ---------------------------------------------------------------------------
def local_response_norm(x, size: int = 5, alpha: float = 1e-4,
                        beta: float = 0.75, k: float = 1.0,
                        data_format: str = "NCHW"):
    """AlexNet LRN (reference local_response_norm): divide by
    (k + alpha/size * Σ_window x²)^beta over a channel window."""
    x = _arr(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    lo = (size - 1) // 2
    hi = size - 1 - lo
    window = [1] * x.ndim
    window[ch_axis] = size
    pads = [(0, 0)] * x.ndim
    pads[ch_axis] = (lo, hi)
    acc = lax.reduce_window(sq, 0.0, lax.add, tuple(window),
                            (1,) * x.ndim, tuple(pads))
    div = jnp.power(k + alpha / size * acc, beta)
    return x / div


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats: bool = True,
                  momentum: float = 0.9, eps: float = 1e-5,
                  data_format: str = "NCHW"):
    """Per-sample per-channel normalization (reference instance_norm)."""
    x = _arr(x)
    enforce(data_format.startswith("NC"),
            "instance_norm supports channel-first layouts")
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if weight is not None:
        y = y * _arr(weight).reshape(shape)
    if bias is not None:
        y = y + _arr(bias).reshape(shape)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Vision (reference nn/functional/vision.py)
# ---------------------------------------------------------------------------
def affine_grid(theta, out_shape, align_corners: bool = True):
    """(N, 2, 3) affine matrices → (N, H, W, 2) sampling grid in [-1, 1]
    coords (reference affine_grid)."""
    theta = _arr(theta).astype(jnp.float32)
    n, c, h, w = out_shape
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2 / h - 1
        xs = (jnp.arange(w) + 0.5) * 2 / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # (H, W, 3)
    # coordinates, not MXU work: the fast low-precision matmul path would
    # shift sample positions by ~1e-3
    return jnp.einsum("hwk,njk->nhwj", base, theta,
                      precision=lax.Precision.HIGHEST)


def grid_sample(x, grid, mode: str = "bilinear",
                padding_mode: str = "zeros", align_corners: bool = True):
    """Sample (N, C, H, W) at (N, Ho, Wo, 2) normalized grid coords
    (reference grid_sample): bilinear/nearest; zeros/border padding."""
    x = _arr(x)
    grid = _arr(grid).astype(jnp.float32)
    n, c, h, w = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    def gather(ix, iy):
        inside = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        if padding_mode == "border":
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
        else:
            ixc = jnp.where(inside, ix, 0)
            iyc = jnp.where(inside, iy, 0)
        vals = x[jnp.arange(n)[:, None, None], :, iyc, ixc]  # (N,Ho,Wo,C)
        if padding_mode == "zeros":
            vals = jnp.where(inside[..., None], vals, 0)
        return vals

    if mode == "nearest":
        out = gather(jnp.round(fx).astype(jnp.int32),
                     jnp.round(fy).astype(jnp.int32))
    else:
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        dx = (fx - x0)[..., None]
        dy = (fy - y0)[..., None]
        out = (gather(x0, y0) * (1 - dx) * (1 - dy)
               + gather(x0 + 1, y0) * dx * (1 - dy)
               + gather(x0, y0 + 1) * (1 - dx) * dy
               + gather(x0 + 1, y0 + 1) * dx * dy)
    return jnp.moveaxis(out, -1, 1).astype(x.dtype)


# ---------------------------------------------------------------------------
# Decoding (reference nn/decode.py gather_tree op)
# ---------------------------------------------------------------------------
def gather_tree(ids, parents):
    """Backtrace beam-search parent pointers into full sequences
    (reference gather_tree op): ids/parents are (T, B, beam)."""
    ids, parents = _arr(ids), _arr(parents)
    T = ids.shape[0]
    beams = jnp.arange(ids.shape[2])[None, :] * jnp.ones(
        (ids.shape[1], 1), jnp.int32)

    def step(carry, t):
        beam = carry
        tok = jnp.take_along_axis(ids[t], beam, axis=1)
        parent = jnp.take_along_axis(parents[t], beam, axis=1)
        return parent.astype(jnp.int32), tok

    _, toks = lax.scan(step, beams.astype(jnp.int32),
                       jnp.arange(T - 1, -1, -1))
    return jnp.flip(toks, axis=0)
