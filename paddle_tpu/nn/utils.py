"""paddle.nn.utils (reference: nn/utils/weight_norm_hook.py:155
``weight_norm``/:202 ``remove_weight_norm``, spectral_norm_hook.py:131
``spectral_norm``, clip_grad convenience).

Reparameterization here rides the Layer forward-pre-hook mechanism: the
wrapped layer keeps ``{name}_g`` (magnitude) and ``{name}_v`` (direction)
Parameters, and the hook recomputes ``weight = g * v / ||v||`` before every
forward — same contract as the reference's hook-based implementation, and
the recompute fuses into the consumer matmul under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.errors import enforce
from .layer import Layer, Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(v, dim: int):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0) -> Layer:
    """Reparameterize ``layer.<name>`` as g * v/||v|| (weight_norm_hook
    :155).  g has the weight's shape collapsed to ``dim``.

    The derived weight is refreshed by a forward-pre hook on every call;
    read it after a forward (not between an ``apply`` and the next eager
    call, when it may still hold the traced value)."""
    enforce(name in layer._parameters,
            f"layer has no parameter {name!r}")
    w = layer._parameters[name].value
    dim = dim % w.ndim
    v = Parameter(w)
    g = Parameter(_norm_except(w, dim))
    layer._parameters[f"{name}_v"] = v
    layer._parameters[f"{name}_g"] = g
    del layer._parameters[name]

    def _recompute(lyr, args):
        vv = lyr._parameters[f"{name}_v"].value
        gg = lyr._parameters[f"{name}_g"].value
        # derived weight lives in the instance dict, NOT _parameters —
        # state_dict/apply see only the (g, v) factors
        object.__setattr__(lyr, name, Parameter(
            gg * vv / jnp.maximum(_norm_except(vv, dim), 1e-12)))
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer.__dict__[f"_{name}_weight_norm_hook"] = (handle, dim)
    _recompute(layer, ())         # materialize for immediate access
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight") -> Layer:
    """Fold g*v/||v|| back into a plain parameter (weight_norm_hook:202)."""
    key = f"_{name}_weight_norm_hook"
    enforce(key in layer.__dict__, f"{name} is not weight-normed")
    handle, dim = layer.__dict__.pop(key)
    layer._forward_pre_hooks.pop(handle, None)
    layer.__dict__.pop(name, None)      # drop the derived instance attr
    v = layer._parameters.pop(f"{name}_v").value
    g = layer._parameters.pop(f"{name}_g").value
    layer._parameters[name] = Parameter(
        g * v / jnp.maximum(_norm_except(v, dim), 1e-12))
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations=1,
                  eps: float = 1e-12, dim: int = 0) -> Layer:
    """Divide ``layer.<name>`` by its largest singular value before every
    forward (spectral_norm_hook:131), using the SpectralNorm layer's
    power-iteration buffers."""
    from .layers import SpectralNorm

    enforce(name in layer._parameters, f"layer has no parameter {name!r}")
    w = layer._parameters[name].value
    sn = SpectralNorm(w.shape, dim=dim, power_iters=n_power_iterations,
                      epsilon=eps)
    layer.__dict__[f"_{name}_spectral_norm"] = sn
    layer._parameters[f"{name}_orig"] = layer._parameters.pop(name)

    def _recompute(lyr, args):
        sn.training = lyr.training
        before = dict(sn._buffers)
        out = sn(lyr._parameters[f"{name}_orig"].value)
        # inside a jit trace the power-iteration buffer update would store
        # tracers (sn lives outside apply's mutation sink) — keep the last
        # eager u/v instead
        import jax.core as _core
        if any(isinstance(b, _core.Tracer) for b in sn._buffers.values()):
            sn._buffers.clear()
            sn._buffers.update(before)
        object.__setattr__(lyr, name, Parameter(out))
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer.__dict__[f"_{name}_spectral_norm_hook"] = handle
    _recompute(layer, ())
    return layer


def parameters_to_vector(parameters) -> jax.Array:
    """Flatten a parameter list into one vector (nn/utils/transform_
    parameters.py)."""
    return jnp.concatenate([jnp.ravel(p.value if isinstance(p, Parameter)
                                      else p) for p in parameters])


def vector_to_parameters(vec, parameters) -> None:
    """Write a flat vector back into the parameter list, in place."""
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if hasattr(p, "shape") else p.value.size
        chunk = vec[offset:offset + n]
        if isinstance(p, Parameter):
            p.value = chunk.reshape(p.shape)
        offset += n
    enforce(offset == vec.size, "vector size mismatch")

