"""Functional op surface (reference: python/paddle/nn/functional/*).

Everything is a pure function over jax arrays (Parameters accepted via
__jax_array__).  AMP policy hooks (see paddle_tpu/amp/state.py) are applied at
the matmul/conv class ops, mirroring the reference tracer's cast insertion
(imperative/tracer.cc:223-231).  Shape/dtype validation plays the role of the
reference's infermeta layer (paddle/phi/infermeta/) — enforced in python at
trace time, for free at runtime.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..amp import state as amp_state
from ..framework import random as fw_random
from ..framework.errors import InvalidArgumentError, enforce
from ..framework.infermeta import (infer_meta, meta_of, require_dim_match,
                                   require_integer, require_rank,
                                   require_rank_in)


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else x


# ---------------------------------------------------------------------------
# Activations (reference: phi/kernels/*_kernel.h activation family)
# ---------------------------------------------------------------------------
def relu(x):
    return jnp.maximum(_arr(x), 0)


def relu6(x):
    return jnp.clip(_arr(x), 0, 6)


def gelu(x, approximate: bool = False):
    return jax.nn.gelu(_arr(x), approximate=approximate)


def silu(x):
    return jax.nn.silu(_arr(x))


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(_arr(x))


def tanh(x):
    return jnp.tanh(_arr(x))


def leaky_relu(x, negative_slope: float = 0.01):
    x = _arr(x)
    return jnp.where(x >= 0, x, negative_slope * x)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(_arr(x), alpha)


def hardswish(x):
    x = _arr(x)
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardsigmoid(x):
    return jnp.clip(_arr(x) / 6.0 + 0.5, 0.0, 1.0)


def mish(x):
    x = _arr(x)
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x, beta: float = 1.0, threshold: float = 20.0):
    x = _arr(x)
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.log1p(jnp.exp(bx)) / beta)


def softmax(x, axis: int = -1):
    x = amp_state.cast_for_op("softmax", _arr(x))
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis: int = -1):
    x = amp_state.cast_for_op("log_softmax", _arr(x))
    return jax.nn.log_softmax(x, axis=axis)


# ---------------------------------------------------------------------------
# Linear / matmul (MXU path; reference phi/kernels/matmul_kernel.h + F.linear)
# ---------------------------------------------------------------------------
def _linear_meta(x, weight, bias=None):
    xm, wm = meta_of(x, "x"), meta_of(weight, "weight")
    require_rank(wm, 2, "linear")
    require_dim_match(xm, xm.ndim - 1, wm, 0, "linear")
    if bias is not None:
        bm = meta_of(bias, "bias")
        if bm.ndim >= 1:   # 0-d scalars broadcast freely
            require_dim_match(bm, -1, wm, 1, "linear")


@infer_meta(_linear_meta)
def linear(x, weight, bias=None):
    """y = x @ W + b with W shaped (in, out) — paddle convention.
    InferMeta: x[..., K] @ W[K, N] (+ b[N]) — phi MatmulInferMeta."""
    x, weight = amp_state.cast_for_op("linear", _arr(x), _arr(weight))
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + _arr(bias).astype(y.dtype)
    return y


def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False):
    x, y = amp_state.cast_for_op("matmul", _arr(x), _arr(y))
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def _embedding_meta(ids, weight, padding_idx=None):
    im, wm = meta_of(ids, "ids"), meta_of(weight, "weight")
    require_integer(im, "embedding")
    require_rank(wm, 2, "embedding")


@infer_meta(_embedding_meta)
def embedding(ids, weight, padding_idx: Optional[int] = None):
    """Reference: phi embedding kernel + nn/functional/input.py.
    InferMeta: integer ids, 2-D weight — phi EmbeddingInferMeta."""
    ids = _arr(ids)
    weight = _arr(weight)
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


# ---------------------------------------------------------------------------
# Convolution / pooling (reference phi conv kernels; NCHW paddle layout —
# XLA's layout assignment re-tiles for the MXU internally)
# ---------------------------------------------------------------------------
def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv2d_meta(x, weight, bias=None, stride=1, padding=0, dilation=1,
                 groups=1, data_format="NCHW"):
    xm, wm = meta_of(x, "x"), meta_of(weight, "weight")
    require_rank(xm, 4, "conv2d")
    require_rank(wm, 4, "conv2d")
    cin = xm.shape[1] if data_format == "NCHW" else xm.shape[3]
    enforce(cin == wm.shape[1] * groups,
            f"conv2d: input channels {cin} != weight in_channels "
            f"{wm.shape[1]} * groups {groups} ({xm} vs {wm})")
    enforce(wm.shape[0] % groups == 0,
            f"conv2d: out_channels {wm.shape[0]} not divisible by "
            f"groups {groups}")
    if bias is not None:
        bm = meta_of(bias, "bias")
        if bm.ndim >= 1:   # 0-d scalars broadcast freely
            require_dim_match(bm, 0, wm, 0, "conv2d")


@infer_meta(_conv2d_meta)
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NCHW"):
    """weight layout (out_ch, in_ch/groups, kh, kw) — paddle/OIHW.
    InferMeta: channel/groups consistency — phi ConvInferMeta."""
    x, weight = amp_state.cast_for_op("conv2d", _arr(x), _arr(weight))
    stride, dilation = _pair(stride), _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding)
        pad = [(p[0], p[0]), (p[1], p[1])]
    # paddle weights are OIHW for BOTH data formats (data_format only
    # describes x); XLA's layout assignment handles the rest
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "OIHW", "NHWC"))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        b = _arr(bias).astype(y.dtype)
        y = y + (b[None, :, None, None] if data_format == "NCHW" else b)
    return y


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    # x: (N, C, L), weight: (O, I, K)
    y = conv2d(x[..., None, :], _arr(weight)[:, :, None, :], bias=bias,
               stride=(1, stride), padding=(0, padding if isinstance(padding, int) else padding[0]),
               dilation=(1, dilation), groups=groups)
    return y[..., 0, :]


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               data_format="NCHW"):
    x = _arr(x)
    k, s = _pair(kernel_size), _pair(stride if stride is not None else kernel_size)
    p = _pair(padding)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out = lax.reduce_window(x, init, lax.max, window, strides, pads)
    if not return_mask:
        return out
    # argmax indices (flattened over the input plane) for max_unpool2d:
    # per-window argmax over patch positions, converted to global offsets.
    # dilated_patches pads with ZEROS (it is a conv with one-hot kernels),
    # which would beat negative maxima and emit out-of-range indices; pad
    # manually with the FINITE dtype minimum first (-inf is unusable here:
    # the one-hot conv computes -inf * 0 = NaN).
    enforce(data_format == "NCHW", "return_mask supports NCHW")
    n, c, h, w = x.shape
    lowest = (jnp.finfo(x.dtype).min
              if jnp.issubdtype(x.dtype, jnp.floating)
              else jnp.iinfo(x.dtype).min)
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
                 constant_values=lowest)
    patches = lax.conv_general_dilated_patches(
        xp, k, s, [(0, 0), (0, 0)],
        dimension_numbers=lax.conv_dimension_numbers(
            xp.shape, (1, c, *k), ("NCHW", "OIHW", "NCHW")))
    oh, ow = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, k[0] * k[1], oh, ow)
    within = jnp.argmax(patches, axis=2)           # (N, C, oh, ow)
    ky = within // k[1]
    kx = within % k[1]
    oy = jnp.arange(oh)[:, None] * s[0] - p[0]
    ox = jnp.arange(ow)[None, :] * s[1] - p[1]
    # clip guards the degenerate real-value == dtype-min tie with padding
    rows = jnp.clip(oy[None, None] + ky, 0, h - 1)
    cols = jnp.clip(ox[None, None] + kx, 0, w - 1)
    mask = (rows * w + cols).astype(jnp.int32)
    return out, mask


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _arr(x)
    k, s = _pair(kernel_size), _pair(stride if stride is not None else kernel_size)
    p = _pair(padding)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window, strides, pads)
    return summed / counts


def _adaptive_edges(in_size: int, out_size: int):
    """Bin o covers input rows [o*in//out, ceil((o+1)*in/out)) —
    torch/paddle adaptive-pool semantics.  Single source of the
    bin-boundary math for both the avg and max adaptive pools."""
    o = np.arange(out_size)
    return (o * in_size) // out_size, -(-((o + 1) * in_size) // out_size)


def _adaptive_bins(in_size: int, out_size: int):
    """Static (idx, mask) per bin, padded to the max bin span."""
    start, end = _adaptive_edges(in_size, out_size)
    span = int((end - start).max())
    offs = start[:, None] + np.arange(span)[None, :]
    return np.minimum(offs, in_size - 1), offs < end[:, None]


def _adaptive_avg_matrix(in_size: int, out_size: int):
    """(out, in) row-stochastic averaging matrix for one spatial axis,
    built at trace time (static shapes), so the general case lowers to
    two MXU matmuls."""
    start, end = _adaptive_edges(in_size, out_size)
    cols = np.arange(in_size)
    m = ((cols >= start[:, None]) & (cols < end[:, None])).astype(np.float32)
    return m / m.sum(axis=1, keepdims=True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _arr(x)
    out_h, out_w = _pair(output_size)
    if data_format == "NCHW":
        in_h, in_w = x.shape[2], x.shape[3]
    else:
        in_h, in_w = x.shape[1], x.shape[2]
    if in_h % out_h == 0 and in_w % out_w == 0:  # fast reduce_window path
        return avg_pool2d(x, (in_h // out_h, in_w // out_w),
                          stride=(in_h // out_h, in_w // out_w),
                          data_format=data_format)
    mh = jnp.asarray(_adaptive_avg_matrix(in_h, out_h), x.dtype)
    mw = jnp.asarray(_adaptive_avg_matrix(in_w, out_w), x.dtype)
    if data_format == "NCHW":
        return jnp.einsum("oh,nchw,pw->ncop", mh, x, mw)
    return jnp.einsum("oh,nhwc,pw->nopc", mh, x, mw)


# ---------------------------------------------------------------------------
# Normalization (reference phi layer_norm/batch_norm kernels)
# ---------------------------------------------------------------------------
def _layer_norm_meta(x, normalized_shape=None, weight=None, bias=None,
                     epsilon=1e-5):
    if normalized_shape is None:
        return
    xm = meta_of(x, "x")
    ns = ((normalized_shape,) if isinstance(normalized_shape, int)
          else tuple(normalized_shape))
    enforce(xm.shape[xm.ndim - len(ns):] == ns,
            f"layer_norm: trailing dims of {xm} != normalized_shape "
            f"{list(ns)}")


@infer_meta(_layer_norm_meta)
def layer_norm(x, normalized_shape=None, weight=None, bias=None,
               epsilon: float = 1e-5):
    x = _arr(x)
    orig_dtype = x.dtype
    xf = amp_state.cast_for_op("layer_norm", x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    naxes = len(normalized_shape) if normalized_shape else 1
    axes = tuple(range(xf.ndim - naxes, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        y = y * _arr(weight).astype(y.dtype)
    if bias is not None:
        y = y + _arr(bias).astype(y.dtype)
    return y.astype(orig_dtype)


def rms_norm(x, weight=None, epsilon: float = 1e-6):
    x = _arr(x)
    orig_dtype = x.dtype
    xf = amp_state.cast_for_op("layer_norm", x)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + epsilon)
    if weight is not None:
        y = y * _arr(weight).astype(y.dtype)
    return y.astype(orig_dtype)


def _batch_norm_meta(x, running_mean, running_var, weight=None, bias=None,
                     training=False, momentum=0.9, epsilon=1e-5,
                     data_format="NCHW"):
    xm = meta_of(x, "x")
    require_rank_in(xm, (2, 3, 4, 5), "batch_norm")
    # must mirror the body's layout rule exactly: "NC*" = channel-first
    c = xm.shape[1] if data_format.startswith("NC") else xm.shape[-1]
    for nm, t in (("running_mean", running_mean),
                  ("running_var", running_var), ("weight", weight),
                  ("bias", bias)):
        if t is not None:
            m = meta_of(t, nm)
            enforce(m.shape == (c,),
                    f"batch_norm: {m} must be ({c},) for {xm}")


@infer_meta(_batch_norm_meta)
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, data_format: str = "NCHW"):
    """Returns (y, new_running_mean, new_running_var)."""
    x = _arr(x)
    orig_dtype = x.dtype
    xf = amp_state.cast_for_op("batch_norm", x)
    # "NC*" formats (NCL/NCHW/NCDHW) are channel-first; "N*C" channel-last
    if data_format.startswith("NC"):
        axes = tuple(i for i in range(x.ndim) if i != 1)
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        shape = (1,) * (x.ndim - 1) + (-1,)
    if training:
        mean = jnp.mean(xf, axis=axes)
        var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
        n = x.size // mean.size
        unbiased = var * n / max(n - 1, 1)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * unbiased
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    y = (xf - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        y = y * _arr(weight).reshape(shape)
    if bias is not None:
        y = y + _arr(bias).reshape(shape)
    return y.astype(orig_dtype), new_rm, new_rv


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5):
    x = _arr(x)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape(n, num_groups, c // num_groups, *x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = (1, c) + (1,) * (x.ndim - 2)
    if weight is not None:
        y = y * _arr(weight).reshape(shape)
    if bias is not None:
        y = y + _arr(bias).reshape(shape)
    return y


# ---------------------------------------------------------------------------
# Dropout (counter-based deterministic RNG under key_scope; reference
# phi dropout kernel + fused_dropout_common.h seed/offset scheme)
# ---------------------------------------------------------------------------
def dropout(x, p: float = 0.5, training: bool = True,
            mode: str = "upscale_in_train", key=None):
    x = _arr(x)
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" or training else x * (1 - p)
    if p == 1.0:
        return jnp.zeros_like(x)
    if key is None:
        key = fw_random.op_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype)).astype(x.dtype)
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


# ---------------------------------------------------------------------------
# Losses (reference phi cross_entropy / softmax_with_cross_entropy kernels)
# ---------------------------------------------------------------------------
def one_hot(x, num_classes: int, dtype=jnp.float32):
    return jax.nn.one_hot(_arr(x), num_classes, dtype=dtype)


def _cross_entropy_meta(logits, label, soft_label=False, reduction="mean",
                        ignore_index=-100, axis=-1, label_smoothing=0.0):
    lm, tm = meta_of(logits, "logits"), meta_of(label, "label")
    if soft_label:
        require_rank(tm, lm.ndim, "cross_entropy")
        require_dim_match(tm, axis if axis >= 0 else tm.ndim + axis,
                          lm, axis if axis >= 0 else lm.ndim + axis,
                          "cross_entropy")
    else:
        require_rank_in(tm, (lm.ndim - 1, lm.ndim), "cross_entropy")
        require_integer(tm, "cross_entropy")


@infer_meta(_cross_entropy_meta)
def cross_entropy(logits, label, soft_label: bool = False,
                  reduction: str = "mean", ignore_index: int = -100,
                  axis: int = -1, label_smoothing: float = 0.0):
    """softmax_with_cross_entropy semantics (reference
    phi/kernels/cross_entropy_kernel.h).  InferMeta: hard labels are
    integer with one fewer (or a squeezable) rank — phi
    CrossEntropyWithSoftmaxInferMeta."""
    logits = amp_state.cast_for_op("cross_entropy", _arr(logits))
    label = _arr(label)
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        if label.ndim == logits.ndim:
            label = jnp.squeeze(label, axis=axis)
        num_classes = logits.shape[axis]
        valid = label != ignore_index
        safe_label = jnp.where(valid, label, 0)
        picked = jnp.take_along_axis(
            logp, safe_label[..., None].astype(jnp.int32), axis=axis)[..., 0]
        if label_smoothing > 0.0:
            smooth = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * smooth
        loss = jnp.where(valid, -picked, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def nll_loss(log_probs, label, reduction: str = "mean"):
    picked = jnp.take_along_axis(
        _arr(log_probs), _arr(label)[..., None].astype(jnp.int32), axis=-1)[..., 0]
    loss = -picked
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def mse_loss(input, label, reduction: str = "mean"):
    d = jnp.square(_arr(input) - _arr(label))
    if reduction == "mean":
        return jnp.mean(d)
    if reduction == "sum":
        return jnp.sum(d)
    return d


def l1_loss(input, label, reduction: str = "mean"):
    d = jnp.abs(_arr(input) - _arr(label))
    if reduction == "mean":
        return jnp.mean(d)
    if reduction == "sum":
        return jnp.sum(d)
    return d


def binary_cross_entropy_with_logits(logit, label, reduction: str = "mean"):
    logit, label = _arr(logit), _arr(label)
    loss = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def smooth_l1_loss(input, label, reduction: str = "mean", delta: float = 1.0):
    d = jnp.abs(_arr(input) - _arr(label))
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# ---------------------------------------------------------------------------
# Attention (XLA reference path; the Pallas fused kernel lives in
# paddle_tpu/ops/attention.py — this is the semantic baseline it must match,
# mirroring reference fused/fmha_ref.h:58 FMHARef)
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                                 is_causal: bool = False, training: bool = True,
                                 scale: Optional[float] = None):
    """q,k,v: (batch, num_heads, seq, head_dim). attn_mask is additive.

    is_causal uses *bottom-right* triangle alignment (k = kv_len - q_len):
    when q_len < kv_len the query block is treated as the suffix of the key
    sequence, which is the KV-cache decode semantics (reference
    fused_attention_op.cc:235 CacheKV path).  The reference's non-cache causal
    mask is top-left aligned, but it only ever runs with q_len == kv_len,
    where the two conventions coincide.
    """
    q, k = amp_state.cast_for_op("attention", _arr(q), _arr(k))
    v = _arr(v)
    head_dim = q.shape[-1]
    if scale is None:
        scale = head_dim ** -0.5
    # route causal/no-mask attention to the Pallas flash kernel when enabled
    # (FLAGS_use_pallas_kernels; reference's fused FMHA path)
    if (is_causal and attn_mask is None
            and (dropout_p == 0.0 or not training) and q.ndim == 4
            and q.shape[-2] % 128 == 0 and k.shape[-2] % 128 == 0
            and head_dim % 8 == 0):
        from ..framework import flags as _flags
        from ..distributed.topology import get_mesh as _get_mesh
        # Route to the Pallas flash kernel only on real TPU (interpret mode
        # on CPU/GPU is for testing, orders of magnitude slower than the
        # einsum path) and with no hybrid mesh active (a pallas_call is
        # opaque to the GSPMD partitioner; the sharded flash path goes
        # through shard_map explicitly).  FLAGS_pallas_interpret_routing
        # forces routing for cross-path tests on CPU.
        if (_flags.get_flag("use_pallas_kernels") and _get_mesh() is None
                and (jax.default_backend() == "tpu"
                     or _flags.get_flag("pallas_interpret_routing"))):
            from ..ops.flash_attention import flash_attention as _fa
            return _fa(q, k, v.astype(q.dtype), causal=True,
                       scale=scale, dropout_p=0.0)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    scores = scores.astype(jnp.float32)
    if attn_mask is not None:
        scores = scores + _arr(attn_mask).astype(scores.dtype)
    if is_causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
        scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    if dropout_p > 0.0 and training:
        probs = dropout(probs, dropout_p, training=True)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference: operators/fused_softmax_mask_upper_
    triangle_op.cu — the GPT attention mask fusion)."""
    x = _arr(x)
    ql, kl = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
    xf = x.astype(jnp.float32)
    xf = jnp.where(causal, xf, jnp.finfo(jnp.float32).min)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)


# ---------------------------------------------------------------------------
# Misc tensor ops
# ---------------------------------------------------------------------------
def pad(x, paddings, mode: str = "constant", value: float = 0.0):
    """paddle.nn.functional.pad semantics: a flat list of (before, after)
    pairs applied to the trailing dims, last dim first — so [l, r, t, b] on a
    4-D NCHW tensor pads W by (l, r) and H by (t, b).  A full ndim*2 list
    pads every dim in order."""
    x = _arr(x)
    paddings = list(paddings)
    enforce(len(paddings) % 2 == 0, "paddings must have an even length")
    npairs = len(paddings) // 2
    enforce(npairs <= x.ndim, "more padding pairs than tensor dims")
    if npairs == x.ndim:
        cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    else:
        # trailing dims, last dim first (paddle/torch flat-pad convention)
        cfg = [(0, 0)] * x.ndim
        for i in range(npairs):
            cfg[x.ndim - 1 - i] = (paddings[2 * i], paddings[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    return jnp.pad(x, cfg, mode=mode)


def clip(x, min=None, max=None):
    return jnp.clip(_arr(x), min, max)


def normalize(x, p: float = 2.0, axis: int = 1, epsilon: float = 1e-12):
    x = _arr(x)
    norm = jnp.maximum(jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True), epsilon)
    return x / norm


def _align_corners_matrix(in_size: int, out_size: int):
    """(out, in) bilinear interpolation matrix with align_corners=True
    sampling (endpoints map to endpoints) — built at trace time, so the
    resize lowers to two matmuls."""
    m = np.zeros((out_size, in_size), np.float32)
    if out_size == 1 or in_size == 1:
        m[:, 0] = 1.0
        return m
    for i in range(out_size):
        pos = i * (in_size - 1) / (out_size - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, in_size - 1)
        frac = pos - lo
        m[i, lo] += 1.0 - frac
        m[i, hi] += frac
    return m


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners: bool = False, data_format="NCHW"):
    x = _arr(x)
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if size is None:
        size = (int(h * scale_factor), int(w * scale_factor))
    enforce(not align_corners or mode == "bilinear",
            f"align_corners is only valid for interpolating modes "
            f"(bilinear), got mode={mode!r}")
    if align_corners and mode == "bilinear":
        mh = jnp.asarray(_align_corners_matrix(h, size[0]), x.dtype)
        mw = jnp.asarray(_align_corners_matrix(w, size[1]), x.dtype)
        if data_format == "NCHW":
            return jnp.einsum("oh,nchw,pw->ncop", mh, x, mw)
        return jnp.einsum("oh,nhwc,pw->nopc", mh, x, mw)
    method = {"nearest": "nearest", "bilinear": "linear"}[mode]
    shape = (n, c, size[0], size[1]) if data_format == "NCHW" \
        else (n, size[0], size[1], c)
    return jax.image.resize(x, shape, method=method)


def flatten(x, start_axis: int = 0, stop_axis: int = -1):
    x = _arr(x)
    nd = x.ndim
    if stop_axis < 0:
        stop_axis += nd
    shape = x.shape[:start_axis] + (-1,) + x.shape[stop_axis + 1:]
    return x.reshape(shape)


# ---------------------------------------------------------------------------
# Extended conv/pool family (reference phi conv3d/conv2d_transpose/pool ops)
# ---------------------------------------------------------------------------
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NCDHW"):
    """x: (N,C,D,H,W), weight: (O, I/g, kD, kH, kW) — reference conv3d_op."""
    x, weight = amp_state.cast_for_op("conv2d", _arr(x), _arr(weight))
    trip = lambda v: (v, v, v) if isinstance(v, int) else tuple(v)
    stride, dilation = trip(stride), trip(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = trip(padding)
        pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW"
        else ("NDHWC", "OIDHW", "NDHWC"))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        b = _arr(bias).astype(y.dtype)
        y = y + (b[None, :, None, None, None] if data_format == "NCDHW"
                 else b)
    return y


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups: int = 1,
                     data_format: str = "NCHW"):
    """Gradient-of-conv deconvolution (reference conv2d_transpose_op).

    weight layout (in_ch, out_ch/groups, kh, kw) — paddle's IOHW transpose
    convention.  Implemented as lax.conv_transpose with explicit padding
    arithmetic: out = (in-1)*s - 2*p + d*(k-1) + 1 + output_padding.
    """
    x, weight = amp_state.cast_for_op("conv2d", _arr(x), _arr(weight))
    s, d = _pair(stride), _pair(dilation)
    p, op = _pair(padding), _pair(output_padding)
    kh = (weight.shape[2] - 1) * d[0] + 1
    kw = (weight.shape[3] - 1) * d[1] + 1
    # lax.conv_transpose padding is on the *output* grid
    pad = [(kh - 1 - p[0], kh - 1 - p[0] + op[0]),
           (kw - 1 - p[1], kw - 1 - p[1] + op[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, (weight.shape[1] * groups, weight.shape[0] // groups,
                  weight.shape[2], weight.shape[3]),
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "OIHW", "NHWC"))
    # flip spatial dims + swap in/out channels: conv_transpose as a
    # dilated conv with the mirrored kernel.  Grouped: input-channel block
    # g maps to output block g — reorder to (out, in/g, kh, kw)
    w = jnp.flip(weight, axis=(2, 3))          # (in, out/g, kh, kw)
    in_g = weight.shape[0] // groups
    w = w.reshape(groups, in_g, weight.shape[1], *weight.shape[2:])
    w = jnp.swapaxes(w, 1, 2)                  # (g, out/g, in_g, kh, kw)
    w = w.reshape(groups * weight.shape[1], in_g, *weight.shape[2:])
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad, lhs_dilation=s,
        rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        b = _arr(bias).astype(y.dtype)
        y = y + (b[None, :, None, None] if data_format == "NCHW" else b)
    return y


def max_pool1d(x, kernel_size, stride=None, padding=0):
    # x: (N, C, L)
    y = max_pool2d(x[..., None, :], (1, kernel_size),
                   (1, stride if stride is not None else kernel_size),
                   (0, padding))
    return y[..., 0, :]


def avg_pool1d(x, kernel_size, stride=None, padding=0):
    y = avg_pool2d(x[..., None, :], (1, kernel_size),
                   (1, stride if stride is not None else kernel_size),
                   (0, padding))
    return y[..., 0, :]


def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    x = _arr(x)
    out_h, out_w = _pair(output_size)
    if data_format == "NCHW":
        in_h, in_w = x.shape[2], x.shape[3]
    else:
        in_h, in_w = x.shape[1], x.shape[2]
    if in_h % out_h == 0 and in_w % out_w == 0:  # fast reduce_window path
        return max_pool2d(x, (in_h // out_h, in_w // out_w),
                          stride=(in_h // out_h, in_w // out_w),
                          data_format=data_format)
    ih, mh = _adaptive_bins(in_h, out_h)
    iw, mw = _adaptive_bins(in_w, out_w)
    neg = jnp.asarray(jnp.finfo(x.dtype).min
                      if jnp.issubdtype(x.dtype, jnp.floating)
                      else jnp.iinfo(x.dtype).min, x.dtype)
    if data_format == "NCHW":
        xh = x[:, :, jnp.asarray(ih), :]            # (N,C,out_h,S,W)
        xh = jnp.where(jnp.asarray(mh)[None, None, :, :, None], xh, neg)
        xh = xh.max(axis=3)                         # (N,C,out_h,W)
        xw = xh[:, :, :, jnp.asarray(iw)]           # (N,C,out_h,out_w,T)
        xw = jnp.where(jnp.asarray(mw)[None, None, None, :, :], xw, neg)
        return xw.max(axis=4)
    xh = x[:, jnp.asarray(ih), :, :]                # (N,out_h,S,W,C)
    xh = jnp.where(jnp.asarray(mh)[None, :, :, None, None], xh, neg)
    xh = xh.max(axis=2)                             # (N,out_h,W,C)
    xw = xh[:, :, jnp.asarray(iw), :]               # (N,out_h,out_w,T,C)
    xw = jnp.where(jnp.asarray(mw)[None, None, :, :, None], xw, neg)
    return xw.max(axis=3)


def pixel_shuffle(x, upscale_factor: int, data_format: str = "NCHW"):
    """(N, C*r^2, H, W) → (N, C, H*r, W*r) — reference pixel_shuffle_op."""
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


def pixel_unshuffle(x, downscale_factor: int, data_format: str = "NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h // r, w // r, c * r * r)


def prelu(x, weight):
    x, w = _arr(x), _arr(weight)
    if w.size > 1 and x.ndim > 1:       # per-channel (NCHW channel axis 1)
        w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x >= 0, x, w * x)


def glu(x, axis: int = -1):
    a, b = jnp.split(_arr(x), 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def cosine_similarity(x1, x2, axis: int = 1, eps: float = 1e-8):
    x1, x2 = _arr(x1), _arr(x2)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def pairwise_distance(x, y, p: float = 2.0, epsilon: float = 1e-6,
                      keepdim: bool = False):
    d = _arr(x) - _arr(y) + epsilon
    return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)


# ---------------------------------------------------------------------------
# Extended losses (reference kldiv_loss_op, margin_rank_loss_op,
# hinge_loss_op, warpctc_op)
# ---------------------------------------------------------------------------
def _reduce(loss, reduction: str):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def kl_div(input, label, reduction: str = "mean"):
    """input is log-probabilities, label is probabilities (kldiv_loss_op).
    'mean' follows paddle: batchmean-style mean over all elements."""
    input, label = _arr(input), _arr(label)
    loss = jnp.where(label > 0, label * (jnp.log(jnp.maximum(label, 1e-30))
                                         - input), 0.0)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin: float = 0.0,
                        reduction: str = "mean"):
    loss = jnp.maximum(0.0, -_arr(label) * (_arr(input) - _arr(other))
                       + margin)
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin: float = 1.0,
                         reduction: str = "mean"):
    input, label = _arr(input), _arr(label)
    loss = jnp.where(label == 1.0, input,
                     jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin: float = 0.0,
                          reduction: str = "mean"):
    sim = cosine_similarity(input1, input2, axis=-1)
    loss = jnp.where(_arr(label) == 1, 1.0 - sim,
                     jnp.maximum(0.0, sim - margin))
    return _reduce(loss, reduction)


def triplet_margin_loss(anchor, positive, negative, margin: float = 1.0,
                        p: float = 2.0, epsilon: float = 1e-6,
                        swap: bool = False, reduction: str = "mean"):
    dp = pairwise_distance(anchor, positive, p, epsilon)
    dn = pairwise_distance(anchor, negative, p, epsilon)
    if swap:
        dn = jnp.minimum(dn, pairwise_distance(positive, negative, p,
                                               epsilon))
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths,
             blank: int = 0, reduction: str = "mean"):
    """CTC forward loss via the standard alpha recursion in log space
    (reference warpctc_op semantics; norm_by_times=False).

    log_probs: (T, B, C) log-softmax outputs; labels: (B, S) padded with
    any value beyond label_lengths.  One lax.scan over time — the DP state
    is the (B, 2S+1) alpha lattice, so the whole loss is one fused TPU
    loop, no host round trips.
    """
    log_probs = _arr(log_probs)
    labels = _arr(labels).astype(jnp.int32)
    T, B, C = log_probs.shape
    S = labels.shape[1]
    NEG = jnp.asarray(-1e30, log_probs.dtype)

    # extended label sequence: blank l1 blank l2 ... lS blank  (2S+1)
    ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths.astype(jnp.int32) + 1

    # can alpha skip from s-2? only when ext[s] != blank and != ext[s-2]
    can_skip = jnp.zeros((B, 2 * S + 1), bool)
    if S > 1:
        can_skip = can_skip.at[:, 3::2].set(labels[:, 1:] != labels[:, :-1])

    pos = jnp.arange(2 * S + 1)[None, :]
    valid = pos < ext_len[:, None]

    emit0 = jnp.take_along_axis(log_probs[0], ext, axis=1)
    alpha0 = jnp.where(pos <= 1, emit0, NEG)
    alpha0 = jnp.where(valid, alpha0, NEG)

    def step(alpha, lp_t):
        # lp_t: (B, C) log probs at time t
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]],
                                   axis=1)
        a_shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]],
                                   axis=1)
        a_shift2 = jnp.where(can_skip, a_shift2, NEG)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
        new = jnp.where(valid, merged + emit, NEG)
        return new, None

    # keep per-step alphas: sequences shorter than T stop at their own
    # input length, gathered at t = input_lengths - 1
    def step_keep(alpha, lp_t):
        new, _ = step(alpha, lp_t)
        return new, new
    _, alphas = lax.scan(step_keep, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)   # (T, B, 2S+1)
    t_idx = (input_lengths.astype(jnp.int32) - 1)[None, :, None]
    final = jnp.take_along_axis(alphas, jnp.broadcast_to(
        t_idx, (1, B, 2 * S + 1)), axis=0)[0]                  # (B, 2S+1)
    last = jnp.take_along_axis(final, (ext_len - 1)[:, None], axis=1)[:, 0]
    second_last = jnp.take_along_axis(
        final, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    # zero-length labels have a single lattice cell: no second path
    second_last = jnp.where(ext_len >= 2, second_last, NEG)
    ll = jnp.logaddexp(last, second_last)
    loss = -ll
    if reduction == "mean":   # paddle/torch: divide by label length
        return jnp.mean(loss / jnp.maximum(label_lengths, 1))
    return _reduce(loss, reduction)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None):
    """Block/CSR-sparse attention (reference nn/functional/
    sparse_attention.py:23, GPU-only op sparse_attention_op.cu).

    q/k/v: (B, H, S, D); offset: (B, H, S+1); columns: (B, H, nnz).
    TPU-native formulation: flatten the CSR pattern and compute the nnz
    scores with gathers + segment softmax (segment_max/segment_sum over the
    row ids) — one fused XLA program, no dynamic shapes.  Masks are
    additive, matching the reference (use -inf to drop a position).
    """
    query, key = amp_state.cast_for_op("attention", _arr(query), _arr(key))
    value = _arr(value)
    S, D = query.shape[2], query.shape[3]
    scale = D ** -0.5

    def one(q, k, v, offset, cols, kpm, am):
        nnz = cols.shape[0]
        row = jnp.searchsorted(offset, jnp.arange(nnz), side="right") - 1
        row = jnp.clip(row, 0, S - 1)
        s = jnp.sum(q[row] * k[cols], axis=-1) * scale
        if kpm is not None:
            s = s + kpm[cols]
        if am is not None:
            s = s + am[row, cols]
        m = jax.ops.segment_max(s, row, num_segments=S)
        m = jnp.where(jnp.isfinite(m), m, 0.0)     # empty rows
        e = jnp.exp(s - m[row])
        z = jax.ops.segment_sum(e, row, num_segments=S)
        p = e / jnp.maximum(z[row], 1e-30)
        return jax.ops.segment_sum(p[:, None] * v[cols], row,
                                   num_segments=S)

    # vmap over heads then batch; masks broadcast per batch
    fn = jax.vmap(one, in_axes=(0, 0, 0, 0, 0, None, None))
    kpm_axes = None if key_padding_mask is None else 0
    fn2 = jax.vmap(fn, in_axes=(0, 0, 0, 0, 0, kpm_axes, None))
    kpm = None if key_padding_mask is None else _arr(key_padding_mask)
    am = None if attn_mask is None else _arr(attn_mask)
    return fn2(query, key, value, _arr(sparse_csr_offset).astype(jnp.int32),
               _arr(sparse_csr_columns).astype(jnp.int32), kpm, am)


def label_smooth(label, prior_dist=None, epsilon: float = 0.1):
    """Smoothed one-hot targets (reference label_smooth_op):
    (1-eps)*label + eps*prior (uniform prior by default).  Integer
    one-hots are promoted to float — a 1/k prior must not truncate."""
    label = _arr(label)
    if not jnp.issubdtype(label.dtype, jnp.floating):
        label = label.astype(jnp.float32)
    k = label.shape[-1]
    if prior_dist is None:
        prior = jnp.full((k,), 1.0 / k, label.dtype)
    else:
        prior = _arr(prior_dist).reshape(-1).astype(label.dtype)
    return (1.0 - epsilon) * label + epsilon * prior


def square_error_cost(input, label):
    """Elementwise (input - label)^2 (reference square_error_cost — the
    static-graph regression staple)."""
    d = _arr(input) - _arr(label)
    return d * d


# long-tail functional surface (reference functional __all__ parity) —
# see _functional_ext.py
from ._functional_ext import *  # noqa: F401,F403,E402
