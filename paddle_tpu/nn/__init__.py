"""paddle_tpu.nn — layers & functional ops (reference: python/paddle/nn)."""
from ..optimizer import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                         ClipGradByValue)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .layer import Layer, LayerList, Parameter, ParameterList, Sequential  # noqa: F401
from .layers import (GELU, SiLU, AdaptiveAvgPool2D, AvgPool2D,  # noqa: F401
                     BatchNorm1D, BatchNorm2D, BatchNorm3D, BCEWithLogitsLoss,
                     Conv2D, CrossEntropyLoss, Dropout, Embedding, Flatten,
                     GroupNorm, Hardsigmoid, Hardswish, L1Loss, LayerNorm,
                     LeakyReLU, Linear, LogSoftmax, MaxPool2D, Mish, MSELoss,
                     MultiHeadAttention, NLLLoss, ReLU, ReLU6, RMSNorm,
                     Sigmoid, SmoothL1Loss, Softmax, Softplus, Tanh,
                     TransformerEncoder, TransformerEncoderLayer)
from .layers import (AdaptiveMaxPool2D, AvgPool1D, Conv1D, Conv3D,  # noqa: F401
                     Conv2DTranspose, CosineEmbeddingLoss, CosineSimilarity,
                     CTCLoss, GLU, HingeEmbeddingLoss, Identity,
                     InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                     KLDivLoss, MarginRankingLoss, MaxPool1D,
                     PairwiseDistance, PixelShuffle, PixelUnshuffle, PReLU,
                     SpectralNorm, Transformer, TransformerDecoder,
                     TransformerDecoderLayer, TripletMarginLoss, Unflatten,
                     Upsample, UpsamplingBilinear2D, UpsamplingNearest2D)
from .rnn import (GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell,  # noqa: F401
                  SimpleRNN, SimpleRNNCell)
from .layers_ext import *  # noqa: F401,F403,E402  (long-tail layer classes)
from .layers_ext import dynamic_decode  # noqa: F401
