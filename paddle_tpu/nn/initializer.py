"""Parameter initializers (reference: python/paddle/nn/initializer/*).

Each initializer is a callable ``init(key, shape, dtype) -> jax.Array`` — the
idiomatic JAX signature — wrapped in a tiny class for paddle-shaped API parity
(``nn.initializer.XavierUniform()`` etc.).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels are OIHW (paddle convention, see nn/layers.py Conv2D):
    # fan_in = in_ch * receptive field, fan_out = out_ch * receptive field.
    receptive = math.prod(shape[2:])
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype=jnp.float32):
        x = self.mean + self.std * jax.random.normal(key, shape, dtype=jnp.float32)
        return x.astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype=jnp.float32):
        x = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class XavierUniform(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


class XavierNormal(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, negative_slope=0.0):
        self.a = negative_slope

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        gain = math.sqrt(2.0 / (1 + self.a ** 2))
        limit = gain * math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, negative_slope=0.0):
        self.a = negative_slope

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        gain = math.sqrt(2.0 / (1 + self.a ** 2))
        std = gain / math.sqrt(fan_in)
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


# paddle-style aliases
constant = Constant
uniform = Uniform
normal = Normal


class Assign(Initializer):
    """Initialize from an explicit array (reference initializer/assign.py)."""

    def __init__(self, value):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32):
        v = jnp.asarray(self.value, dtype)
        if tuple(v.shape) != tuple(shape):
            raise ValueError(f"Assign value shape {v.shape} != {shape}")
        return v


class Dirac(Initializer):
    """Identity-preserving conv init (reference initializer/dirac.py):
    out[i, i % in, center...] = 1 within each of ``groups`` blocks."""

    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, key, shape, dtype=jnp.float32):
        if len(shape) < 3:
            raise ValueError("Dirac needs a conv-shaped (O, I, *k) weight")
        out_ch, in_ch = shape[0], shape[1]
        if out_ch % self.groups:
            raise ValueError("out_channels must divide by groups")
        w = np.zeros(shape, np.float32)
        center = tuple(k // 2 for k in shape[2:])
        per_group = out_ch // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_ch)):
                w[(g * per_group + i, i) + center] = 1.0
        return jnp.asarray(w, dtype)


class Orthogonal(Initializer):
    """(Semi-)orthogonal matrix init via QR (reference
    initializer/orthogonal.py); tensors are flattened to 2-D."""

    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype=jnp.float32):
        if len(shape) < 2:
            raise ValueError("Orthogonal needs >= 2 dims")
        rows = shape[0]
        cols = 1
        for s in shape[1:]:
            cols *= s
        n, m = max(rows, cols), min(rows, cols)
        a = jax.random.normal(key, (n, m), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))     # unique decomposition
        q = q.T if rows < cols else q
        return (self.gain * q.reshape(shape)).astype(dtype)


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/fluid/param_attr.py
    ``ParamAttr`` — name/initializer/trainable; regularizer and lr are handled
    by the optimizer's apply_decay_param_fun / LRScheduler on TPU)."""

    def __init__(self, name=None, initializer=None, trainable=True,
                 learning_rate=1.0, regularizer=None, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.trainable = trainable
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.need_clip = need_clip


def calculate_gain(nonlinearity: str, param=None) -> float:
    """Recommended init gain per nonlinearity (reference
    initializer.calculate_gain)."""
    import math
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0), "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        slope = 0.01 if param is None else float(param)
        return math.sqrt(2.0 / (1 + slope ** 2))
    if nonlinearity in gains:
        return gains[nonlinearity]
    raise ValueError(f"unknown nonlinearity {nonlinearity!r}")


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    initializer/Bilinear): weight (C_in, C_out, k, k) gets the classic
    interpolation stencil per channel pair's diagonal."""

    def __call__(self, key, shape, dtype=jnp.float32):
        from ..framework.errors import enforce
        enforce(len(shape) == 4, "Bilinear init expects a 4-D conv weight")
        k = shape[-1]
        enforce(shape[-2] == k, "Bilinear init expects square kernels")
        f = (k + 1) // 2
        c = f - 1 if k % 2 == 1 else f - 0.5
        og = np.ogrid[:k, :k]
        filt = ((1 - np.abs(og[0] - c) / f)
                * (1 - np.abs(og[1] - c) / f)).astype(np.float32)
        w = np.broadcast_to(filt, shape).copy()
        return jnp.asarray(w, dtype)


_global_initializer = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """Reference set_global_initializer: default initializers used by
    Layer.create_parameter when no per-parameter initializer is given.
    Pass (None, None) to reset."""
    _global_initializer["weight"] = weight_init
    _global_initializer["bias"] = bias_init
