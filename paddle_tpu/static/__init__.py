"""paddle.static migration facade (L7 static-graph surface).

The reference maintains a whole second programming model — Program/Block
IR, append_backward, four executors (SURVEY C19-C23).  This framework
deliberately has ONE codepath: a jitted function IS the static program
(SURVEY A13 records the justification).  This module keeps the static
API's *shape* so ported scripts have landing points, with each symbol
mapped to its one-codepath equivalent:

- ``static.data`` / ``InputSpec``  → trace-time specs (feed declarations)
- ``Program`` / ``program_guard`` / ``default_main_program`` → a Program
  here is just a named scope holding a traced callable; building ops
  imperatively inside the guard is not supported (write a function and
  ``jit`` it — that's the static graph)
- ``Executor.run(program, feed, fetch_list)`` → calls the program's
  callable under jit with the feed dict
- ``save_inference_model`` / ``load_inference_model`` → the jit.save /
  jit.load StableHLO artifact
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..jit import InputSpec

__all__ = ["InputSpec", "data", "Program", "program_guard",
           "default_main_program", "default_startup_program", "Executor",
           "save_inference_model", "load_inference_model", "nn"]


def data(name: str, shape: Sequence[Optional[int]], dtype="float32"):
    """Feed declaration (reference static.data) → InputSpec."""
    return InputSpec(shape, dtype=dtype, name=name)


class Program:
    """A named scope for one traced callable (the one-codepath rendering of
    ProgramDesc).  Set the callable with ``set_fn`` (signature
    ``fn(**feed) -> output or dict``); Executor.run jits and runs it."""

    def __init__(self, name: str = "main"):
        self.name = name
        self._fn: Optional[Callable] = None
        self._jitted = None
        # static.nn parameter store: layers created by the nn helpers are
        # cached per program by deterministic build order, so a retrace
        # (new batch shape) reuses the SAME weights instead of redrawing
        self._nn_layers: Dict[str, Any] = {}
        self._nn_counters: Dict[str, int] = {}

    def _nn_slot(self, kind: str, name: Optional[str]) -> str:
        if name:
            return name
        idx = self._nn_counters.get(kind, 0)
        self._nn_counters[kind] = idx + 1
        return f"{kind}_{idx}"

    def set_fn(self, fn: Callable) -> "Program":
        self._fn = fn

        def _traced(feed):
            # reset build-order counters so every (re)trace walks the
            # helpers in the same deterministic sequence
            self._nn_counters.clear()
            with program_guard(self):
                return fn(**feed)

        self._jitted = jax.jit(_traced)
        return self

    def run(self, feed: Dict[str, Any]):
        enforce(self._fn is not None,
                f"Program {self.name!r} has no function attached — build "
                "static programs as python functions (Program.set_fn) and "
                "jit compiles them; imperative op-building has no analog")
        return self._jitted({k: jnp.asarray(np.asarray(v))
                             for k, v in feed.items()})

    def clone(self, for_test: bool = False) -> "Program":
        p = Program(self.name)
        p._fn, p._jitted = self._fn, self._jitted
        return p


_default_main = Program("main")
_default_startup = Program("startup")


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    """Source-compat scope: temporarily makes ``main_program`` the default.
    (Params initialize at Layer construction, so startup programs carry
    nothing here.)"""
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


class Executor:
    """Reference static.Executor facade: ``run`` executes a Program's
    callable; place selection is owned by jax (the device the arrays live
    on), kept as an argument for source compat."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[List] = None, return_numpy: bool = True):
        program = program or default_main_program()
        out = program.run(feed or {})
        if isinstance(out, dict):
            keys = fetch_list or list(out.keys())
            outs = [out[k] for k in keys]
        elif isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return list(outs)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         *, layer=None, input_spec=None, **kw):
    """→ jit.save (the StableHLO artifact).  Pass the Layer via ``layer``
    (the Program-IR route has no analog)."""
    from .. import jit as pt_jit
    enforce(layer is not None,
            "save_inference_model on TPU exports a Layer: pass layer=<Layer>"
            " and input_spec=[InputSpec...] (≙ jit.save)")
    specs = input_spec if input_spec is not None else feed_vars
    enforce(specs is not None,
            "save_inference_model needs input specs: pass "
            "input_spec=[InputSpec...] (or feed_vars from static.data)")
    pt_jit.save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None):
    from .. import jit as pt_jit
    loaded = pt_jit.load(path_prefix)
    feed_names = [s.name or f"input_{i}"
                  for i, s in enumerate(loaded.input_spec)]
    return loaded, feed_names, None


class nn:
    """paddle.static.nn source-compat namespace (reference static/nn/
    common.py fc, input.py embedding, ...).

    Helpers cache their layers on the current default Program keyed by
    build order (or explicit ``name``), with weights materialized at
    compile time (``jax.ensure_compile_time_eval``) — a jit retrace
    reuses the same parameters, matching the reference's
    program-owns-the-parameters model."""

    @staticmethod
    def _layer(kind, name, build):
        prog = default_main_program()
        slot = prog._nn_slot(kind, name)
        if slot not in prog._nn_layers:
            with jax.ensure_compile_time_eval():
                prog._nn_layers[slot] = build()
        return prog._nn_layers[slot]

    @staticmethod
    def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
           bias_attr=None, activation=None, name=None):
        """Reference signature order (static/nn/common.py fc)."""
        from ..nn import functional as F
        from ..nn.layers import Linear
        import jax.numpy as jnp

        x = jnp.asarray(x)
        lead = x.shape[:num_flatten_dims]
        flat = x.reshape(*lead, -1)
        layer = nn._layer("fc", name, lambda: Linear(
            flat.shape[-1], size, weight_attr=weight_attr,
            bias_attr=bias_attr))
        out = layer(flat)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse: bool = False, padding_idx=None,
                  param_attr=None, dtype="float32", name=None):
        from ..nn.layers import Embedding

        layer = nn._layer("embedding", name, lambda: Embedding(
            size[0], size[1], padding_idx=padding_idx,
            weight_attr=param_attr, dtype=dtype))
        return layer(input)

    @staticmethod
    def batch_norm(input, act=None, momentum: float = 0.9,
                   epsilon: float = 1e-5, data_layout: str = "NCHW",
                   name=None, **kw):
        from ..nn import functional as F
        from ..nn.layers import BatchNorm2D

        enforce(not kw, f"batch_norm got unsupported kwargs {sorted(kw)}")
        features = input.shape[1] if data_layout == "NCHW" \
            else input.shape[-1]
        layer = nn._layer("batch_norm", name, lambda: BatchNorm2D(
            features, momentum=momentum, epsilon=epsilon,
            data_format=data_layout))
        out = layer(input)
        return getattr(F, act)(out) if act else out


# ---------------------------------------------------------------------------
# Static long-tail surface (reference static/__init__.py __all__ parity).
# The stance is unchanged (module docstring): Program is a scope around one
# traced callable.  Real capabilities (EMA, state save/load, scopes,
# py_func/Print, places) are implemented; pre-2.0 graph-surgery entry
# points (append_backward/gradients) raise with the functional recipe.
# ---------------------------------------------------------------------------
import contextlib as _contextlib

Variable = InputSpec      # the declared-tensor role in this facade


def name_scope(prefix: str = None):
    """Reference static.name_scope: a name prefix for ops — naming only
    in the one-jit design; kept as a context manager for ported code."""
    return _contextlib.nullcontext(prefix)


def device_guard(device: str = None):
    """Reference static.device_guard: op placement hint.  XLA owns
    placement; the guard is accepted and ignored (documented)."""
    return _contextlib.nullcontext(device)


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


@_contextlib.contextmanager
def scope_guard(scope: _Scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield scope
    finally:
        _global_scope = prev


def cpu_places(device_count: Optional[int] = None):
    from ..framework import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..framework import TPUPlace
    import jax as _jax
    ids = device_ids if device_ids is not None \
        else range(len(_jax.devices()))
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places
npu_places = cuda_places
mlu_places = cuda_places


def create_global_var(shape, value, dtype, persistable: bool = False,
                      force_cpu: bool = False, name=None):
    """A named global tensor in the current scope (reference
    create_global_var)."""
    from ..framework.dtype import convert_dtype
    v = jnp.full(tuple(shape), value, convert_dtype(dtype))
    _global_scope[name or f"gvar_{len(_global_scope)}"] = v
    return v


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .. import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def Print(input, first_n: int = -1, message: Optional[str] = None,  # noqa: A002
          summarize: int = 20, print_tensor_name: bool = True, **kw):
    """Reference static.Print op: print a tensor during execution —
    jax.debug.print works inside jit (the op's role)."""
    jax.debug.print((message or "") + " {x}", x=input)
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static.py_func: host-python op in the graph — the
    pure_callback bridge (utils/cpp_extension.py host-op machinery)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    shape_dtype = jax.tree_util.tree_map(
        lambda o: jax.ShapeDtypeStruct(tuple(o.shape), o.dtype), out)
    return jax.pure_callback(func, shape_dtype, *xs)


def accuracy(input, label, k: int = 1, **kw):  # noqa: A002
    """Top-k accuracy op (reference static.accuracy)."""
    topk = jnp.argsort(jnp.asarray(input), axis=-1)[..., -k:]
    lbl = jnp.asarray(label).reshape(-1, 1)
    return jnp.mean(jnp.any(topk == lbl, axis=-1).astype(jnp.float32))


def auc(input, label, curve: str = "ROC", num_thresholds: int = 4095, **kw):  # noqa: A002
    """Streaming-free AUC op over one batch (reference static.auc)."""
    from ..metric import Auc
    m = Auc(num_thresholds=num_thresholds)
    m.update(jnp.asarray(input), jnp.asarray(label))
    return jnp.asarray(m.accumulate(), jnp.float32)


class ExponentialMovingAverage:
    """Reference static.ExponentialMovingAverage: shadow parameters
    ema = decay*ema + (1-decay)*param with bias correction; apply()
    temporarily swaps shadows in (restore() swaps back).  Functional
    form: ``update(params)`` returns None (state held here);
    ``shadow()`` returns the corrected averages."""

    def __init__(self, decay: float = 0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = None
        self._step = 0
        self._backup = None

    def update(self, params):
        params = {k: jnp.asarray(v) for k, v in params.items()}
        if self._ema is None:
            self._ema = {k: jnp.zeros_like(v) for k, v in params.items()}
        d = self._decay
        self._ema = {k: d * self._ema[k] + (1 - d) * params[k]
                     for k in params}
        self._step += 1

    def shadow(self):
        enforce(self._ema is not None, "EMA.update never called")
        corr = 1 - self._decay ** self._step
        return {k: v / corr for k, v in self._ema.items()}

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        yield self.shadow()

    def restore(self, executor=None):
        pass


class WeightNormParamAttr:
    """Reference static.WeightNormParamAttr: ParamAttr requesting weight
    normalization — the dygraph path implements it via
    nn.utils.weight_norm hooks; this records dim + the attr fields."""

    def __init__(self, dim=None, name=None, initializer=None, trainable=True,
                 **kw):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


class BuildStrategy:
    """Graph-pass configuration (reference BuildStrategy).  XLA owns the
    pass pipeline; the knobs are recorded so ported scripts construct and
    set them freely (documented no-ops)."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        return self.__dict__.get("_opts", {}).get(k, False)


class ExecutionStrategy(BuildStrategy):
    pass


class CompiledProgram:
    """Reference CompiledProgram(program).with_data_parallel(...): the
    one-XLA-compilation design makes this a pass-through wrapper whose
    run delegates to the wrapped Program (GSPMD covers data parallel)."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self

    def run(self, feed):
        return self._program.run(feed)


class ParallelExecutor(CompiledProgram):
    def __init__(self, use_cuda: bool = False, loss_name=None,
                 main_program=None, build_strategy=None,
                 exec_strategy=None, scope=None, share_vars_from=None):
        super().__init__(main_program or default_main_program())


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Pre-2.0 graph surgery (reference append_backward): inserting grad
    ops into a ProgramDesc has no analog when jax.grad IS the backward.
    Raises with the functional recipe (docs/MIGRATION.md: static)."""
    raise NotImplementedError(
        "append_backward rewrites a ProgramDesc; in this runtime the "
        "backward is jax.value_and_grad over the program's python "
        "function — build the train step functionally "
        "(docs/MIGRATION.md: 'static graphs').")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    raise NotImplementedError(
        "static.gradients rewrites a ProgramDesc; use "
        "paddle_tpu.autograd.grad / jax.grad over a function of the "
        "inputs (docs/MIGRATION.md: 'static graphs').")


# --- program/persistables serialization (delegates to the jit/io stack) --
def save(program: Program, model_path: str, protocol: int = 4):
    """Persist the scope's variables for a Program (reference
    static.save): parameters live in the program's nn layer store."""
    from ..framework.io import save as _save
    state = {k: getattr(l, "state_dict", lambda: {})()
             for k, l in program._nn_layers.items()}
    _save(state, model_path + ".pdparams")


def load(program: Program, model_path: str, executor=None, var_list=None):
    from ..framework.io import load as _load
    state = _load(model_path + ".pdparams")
    for k, sub in state.items():
        if k in program._nn_layers and hasattr(program._nn_layers[k],
                                               "set_state_dict"):
            program._nn_layers[k].set_state_dict(sub)
    return state


def serialize_program(feed_vars, fetch_vars, **kwargs) -> bytes:
    import pickle
    return pickle.dumps({"feed": [getattr(v, "name", None) for v in feed_vars],
                         "fetch": [getattr(v, "name", None) for v in fetch_vars]})


def deserialize_program(data: bytes):
    import pickle
    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, executor=None) -> bytes:
    import pickle
    prog = default_main_program()
    state = {k: getattr(l, "state_dict", lambda: {})()
             for k, l in prog._nn_layers.items()}
    state = jax.tree_util.tree_map(lambda x: np.asarray(x), state)
    return pickle.dumps(state)


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle
    state = pickle.loads(data)
    for k, sub in state.items():
        if k in program._nn_layers and hasattr(program._nn_layers[k],
                                               "set_state_dict"):
            program._nn_layers[k].set_state_dict(sub)
    return state


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    return program


def load_program_state(model_path: str, var_list=None):
    from ..framework.io import load as _load
    return _load(model_path + ".pdparams")


def set_program_state(program, state_dict):
    for k, sub in state_dict.items():
        if k in program._nn_layers and hasattr(program._nn_layers[k],
                                               "set_state_dict"):
            program._nn_layers[k].set_state_dict(sub)


class IpuStrategy:       # IPU backends have no TPU counterpart; config
    def __init__(self):  # shells keep ported scripts importable (N/A in
        self._opts = {}  # docs/MIGRATION.md)

    def set_graph_config(self, **kw):
        self._opts.update(kw)


class IpuCompiledProgram(CompiledProgram):
    pass


def ipu_shard_guard(index: int = -1, stage: int = -1):
    return _contextlib.nullcontext()


__all__ += ["Variable", "name_scope", "device_guard", "global_scope",
            "scope_guard", "cpu_places", "cuda_places", "xpu_places",
            "npu_places", "mlu_places", "create_global_var",
            "create_parameter", "Print", "py_func", "accuracy", "auc",
            "ExponentialMovingAverage", "WeightNormParamAttr",
            "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
            "ParallelExecutor", "append_backward", "gradients", "save",
            "load", "serialize_program", "deserialize_program",
            "serialize_persistables", "deserialize_persistables",
            "save_to_file", "load_from_file", "normalize_program",
            "load_program_state", "set_program_state", "IpuStrategy",
            "IpuCompiledProgram", "ipu_shard_guard"]
