"""paddle.static migration facade (L7 static-graph surface).

The reference maintains a whole second programming model — Program/Block
IR, append_backward, four executors (SURVEY C19-C23).  This framework
deliberately has ONE codepath: a jitted function IS the static program
(SURVEY A13 records the justification).  This module keeps the static
API's *shape* so ported scripts have landing points, with each symbol
mapped to its one-codepath equivalent:

- ``static.data`` / ``InputSpec``  → trace-time specs (feed declarations)
- ``Program`` / ``program_guard`` / ``default_main_program`` → a Program
  here is just a named scope holding a traced callable; building ops
  imperatively inside the guard is not supported (write a function and
  ``jit`` it — that's the static graph)
- ``Executor.run(program, feed, fetch_list)`` → calls the program's
  callable under jit with the feed dict
- ``save_inference_model`` / ``load_inference_model`` → the jit.save /
  jit.load StableHLO artifact
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..jit import InputSpec

__all__ = ["InputSpec", "data", "Program", "program_guard",
           "default_main_program", "default_startup_program", "Executor",
           "save_inference_model", "load_inference_model", "nn"]


def data(name: str, shape: Sequence[Optional[int]], dtype="float32"):
    """Feed declaration (reference static.data) → InputSpec."""
    return InputSpec(shape, dtype=dtype, name=name)


class Program:
    """A named scope for one traced callable (the one-codepath rendering of
    ProgramDesc).  Set the callable with ``set_fn`` (signature
    ``fn(**feed) -> output or dict``); Executor.run jits and runs it."""

    def __init__(self, name: str = "main"):
        self.name = name
        self._fn: Optional[Callable] = None
        self._jitted = None
        # static.nn parameter store: layers created by the nn helpers are
        # cached per program by deterministic build order, so a retrace
        # (new batch shape) reuses the SAME weights instead of redrawing
        self._nn_layers: Dict[str, Any] = {}
        self._nn_counters: Dict[str, int] = {}

    def _nn_slot(self, kind: str, name: Optional[str]) -> str:
        if name:
            return name
        idx = self._nn_counters.get(kind, 0)
        self._nn_counters[kind] = idx + 1
        return f"{kind}_{idx}"

    def set_fn(self, fn: Callable) -> "Program":
        self._fn = fn

        def _traced(feed):
            # reset build-order counters so every (re)trace walks the
            # helpers in the same deterministic sequence
            self._nn_counters.clear()
            with program_guard(self):
                return fn(**feed)

        self._jitted = jax.jit(_traced)
        return self

    def run(self, feed: Dict[str, Any]):
        enforce(self._fn is not None,
                f"Program {self.name!r} has no function attached — build "
                "static programs as python functions (Program.set_fn) and "
                "jit compiles them; imperative op-building has no analog")
        return self._jitted({k: jnp.asarray(np.asarray(v))
                             for k, v in feed.items()})

    def clone(self, for_test: bool = False) -> "Program":
        p = Program(self.name)
        p._fn, p._jitted = self._fn, self._jitted
        return p


_default_main = Program("main")
_default_startup = Program("startup")


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    """Source-compat scope: temporarily makes ``main_program`` the default.
    (Params initialize at Layer construction, so startup programs carry
    nothing here.)"""
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    # trace-time scope bookkeeping, not traced state: the default-program
    # pointer is swapped so static.nn helpers resolve the right Program
    # while its function traces, and restored in the finally below
    _default_main = main_program  # noqa: trace — restored in finally, see above
    if startup_program is not None:
        _default_startup = startup_program  # noqa: trace — restored in finally, see above
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


class Executor:
    """Reference static.Executor facade: ``run`` executes a Program's
    callable; place selection is owned by jax (the device the arrays live
    on), kept as an argument for source compat."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[List] = None, return_numpy: bool = True):
        program = program or default_main_program()
        out = program.run(feed or {})
        if isinstance(out, dict):
            keys = fetch_list or list(out.keys())
            outs = [out[k] for k in keys]
        elif isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return list(outs)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         *, layer=None, input_spec=None, **kw):
    """→ jit.save (the StableHLO artifact).  Pass the Layer via ``layer``
    (the Program-IR route has no analog)."""
    from .. import jit as pt_jit
    enforce(layer is not None,
            "save_inference_model on TPU exports a Layer: pass layer=<Layer>"
            " and input_spec=[InputSpec...] (≙ jit.save)")
    specs = input_spec if input_spec is not None else feed_vars
    enforce(specs is not None,
            "save_inference_model needs input specs: pass "
            "input_spec=[InputSpec...] (or feed_vars from static.data)")
    pt_jit.save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None):
    from .. import jit as pt_jit
    loaded = pt_jit.load(path_prefix)
    feed_names = [s.name or f"input_{i}"
                  for i, s in enumerate(loaded.input_spec)]
    return loaded, feed_names, None


class nn:
    """paddle.static.nn source-compat namespace (reference static/nn/
    common.py fc, input.py embedding, ...).

    Helpers cache their layers on the current default Program keyed by
    build order (or explicit ``name``), with weights materialized at
    compile time (``jax.ensure_compile_time_eval``) — a jit retrace
    reuses the same parameters, matching the reference's
    program-owns-the-parameters model."""

    @staticmethod
    def _layer(kind, name, build):
        prog = default_main_program()
        slot = prog._nn_slot(kind, name)
        if slot not in prog._nn_layers:
            with jax.ensure_compile_time_eval():
                prog._nn_layers[slot] = build()
        return prog._nn_layers[slot]

    @staticmethod
    def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
           bias_attr=None, activation=None, name=None):
        """Reference signature order (static/nn/common.py fc)."""
        from ..nn import functional as F
        from ..nn.layers import Linear
        import jax.numpy as jnp

        x = jnp.asarray(x)
        lead = x.shape[:num_flatten_dims]
        flat = x.reshape(*lead, -1)
        layer = nn._layer("fc", name, lambda: Linear(
            flat.shape[-1], size, weight_attr=weight_attr,
            bias_attr=bias_attr))
        out = layer(flat)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse: bool = False, padding_idx=None,
                  param_attr=None, dtype="float32", name=None):
        from ..nn.layers import Embedding

        layer = nn._layer("embedding", name, lambda: Embedding(
            size[0], size[1], padding_idx=padding_idx,
            weight_attr=param_attr, dtype=dtype))
        return layer(input)

    @staticmethod
    def batch_norm(input, act=None, momentum: float = 0.9,
                   epsilon: float = 1e-5, data_layout: str = "NCHW",
                   name=None, **kw):
        from ..nn import functional as F
        from ..nn.layers import BatchNorm2D

        enforce(not kw, f"batch_norm got unsupported kwargs {sorted(kw)}")
        features = input.shape[1] if data_layout == "NCHW" \
            else input.shape[-1]
        layer = nn._layer("batch_norm", name, lambda: BatchNorm2D(
            features, momentum=momentum, epsilon=epsilon,
            data_format=data_layout))
        out = layer(input)
        return getattr(F, act)(out) if act else out

    # -- conv / norm family (reference static/nn/common.py), all program-
    # -- cached like fc/embedding/batch_norm above -------------------------
    @staticmethod
    def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,  # noqa: A002
               dilation=1, groups: int = 1, param_attr=None, bias_attr=None,
               act=None, data_format: str = "NCHW", name=None):
        from ..nn import functional as F
        from ..nn.layers import Conv2D

        cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
        k = filter_size if isinstance(filter_size, int) else tuple(filter_size)
        layer = nn._layer("conv2d", name, lambda: Conv2D(
            cin, num_filters, k, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr, data_format=data_format))
        out = layer(input)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def conv3d(input, num_filters: int, filter_size, stride=1, padding=0,  # noqa: A002
               dilation=1, groups: int = 1, param_attr=None, bias_attr=None,
               act=None, data_format: str = "NCDHW", name=None):
        from ..nn import functional as F
        from ..nn.layers import Conv3D

        cin = input.shape[1]
        layer = nn._layer("conv3d", name, lambda: Conv3D(
            cin, num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr))
        out = layer(input)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def _transpose_kernel(in_sp, output_size, stride, padding, dilation,
                          nd):
        """Derive the kernel from output_size (reference semantics when
        filter_size is omitted): out = (in-1)*s - 2*p + d*(k-1) + 1."""
        def tup(v):
            return (v,) * nd if isinstance(v, int) else tuple(v)
        out = tup(output_size)
        s_, p_, d_ = tup(stride), tup(padding), tup(dilation)
        k = []
        for i in range(nd):
            num = out[i] - (in_sp[i] - 1) * s_[i] + 2 * p_[i] - 1
            enforce(num % d_[i] == 0 and num // d_[i] + 1 >= 1,
                    f"output_size {out[i]} unreachable from input "
                    f"{in_sp[i]} with stride {s_[i]} padding {p_[i]}")
            k.append(num // d_[i] + 1)
        return tuple(k)

    @staticmethod
    def conv2d_transpose(input, num_filters: int, filter_size=None,  # noqa: A002
                         output_size=None, stride=1, padding=0, dilation=1,
                         groups: int = 1, param_attr=None, bias_attr=None,
                         act=None, data_format: str = "NCHW", name=None):
        from ..nn import functional as F
        from ..nn.layers import Conv2DTranspose

        cin = input.shape[1]
        if filter_size is None:
            enforce(output_size is not None,
                    "conv2d_transpose needs filter_size or output_size")
            filter_size = nn._transpose_kernel(
                input.shape[2:], output_size, stride, padding, dilation, 2)
        layer = nn._layer("conv2d_transpose", name, lambda: Conv2DTranspose(
            cin, num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr))
        out = layer(input)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def conv3d_transpose(input, num_filters: int, filter_size=None,  # noqa: A002
                         output_size=None, stride=1, padding=0, dilation=1,
                         groups: int = 1, param_attr=None, bias_attr=None,
                         act=None, data_format: str = "NCDHW", name=None):
        from ..nn import functional as F
        from ..nn.layers_ext import Conv3DTranspose

        cin = input.shape[1]
        if filter_size is None:
            enforce(output_size is not None,
                    "conv3d_transpose needs filter_size or output_size")
            filter_size = nn._transpose_kernel(
                input.shape[2:], output_size, stride, padding, dilation, 3)
        layer = nn._layer("conv3d_transpose", name, lambda: Conv3DTranspose(
            cin, num_filters, filter_size, stride=stride, padding=padding,
            dilation=dilation, groups=groups, weight_attr=param_attr,
            bias_attr=bias_attr))
        out = layer(input)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def deform_conv2d(input, offset, mask, num_filters: int, filter_size,  # noqa: A002
                      stride=1, padding=0, dilation=1, groups: int = 1,
                      deformable_groups: int = 1, im2col_step: int = 1,
                      param_attr=None, bias_attr=None, name=None):
        from .. import create_parameter
        from ..vision.ops import deform_conv2d as _dc

        cin = input.shape[1]
        k = (filter_size, filter_size) if isinstance(filter_size, int) \
            else tuple(filter_size)

        def build():
            w = create_parameter([num_filters, cin // groups, *k],
                                 "float32", attr=param_attr)
            b = None if bias_attr is False else create_parameter(
                [num_filters], "float32", attr=bias_attr, is_bias=True)
            return (w, b)

        w, b = nn._layer("deform_conv2d", name, build)
        return _dc(input, offset, w.value, bias=(None if b is None
                                                 else b.value),
                   stride=stride, padding=padding, dilation=dilation,
                   groups=groups, deformable_groups=deformable_groups,
                   mask=mask)

    @staticmethod
    def layer_norm(input, scale: bool = True, shift: bool = True,  # noqa: A002
                   begin_norm_axis: int = 1, epsilon: float = 1e-5,
                   param_attr=None, bias_attr=None, act=None, name=None):
        from ..nn import functional as F
        import jax.numpy as jnp

        x = jnp.asarray(input)
        shape = x.shape[begin_norm_axis:]

        def build():
            from .. import create_parameter
            from ..nn.initializer import Constant
            w = create_parameter(list(shape), "float32", attr=param_attr,
                                 default_initializer=Constant(1.0)) \
                if scale else None
            b = create_parameter(list(shape), "float32", attr=bias_attr,
                                 is_bias=True) if shift else None
            return (w, b)

        w, b = nn._layer("layer_norm", name, build)
        out = F.layer_norm(x, shape, None if w is None else w.value,
                           None if b is None else b.value, epsilon)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def group_norm(input, groups: int, epsilon: float = 1e-5,  # noqa: A002
                   param_attr=None, bias_attr=None, act=None,
                   data_layout: str = "NCHW", name=None):
        from ..nn import functional as F
        from ..nn.layers import GroupNorm

        enforce(data_layout == "NCHW",
                "static.nn.group_norm supports NCHW (the functional "
                "group_norm is channel-first)")
        c = input.shape[1]
        layer = nn._layer("group_norm", name, lambda: GroupNorm(
            groups, c, epsilon=epsilon, weight_attr=param_attr,
            bias_attr=bias_attr))
        out = layer(input)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def instance_norm(input, epsilon: float = 1e-5, param_attr=None,  # noqa: A002
                      bias_attr=None, name=None):
        from ..nn.layers import InstanceNorm2D

        c = input.shape[1]
        layer = nn._layer("instance_norm", name, lambda: InstanceNorm2D(
            c, epsilon=epsilon, weight_attr=param_attr,
            bias_attr=bias_attr))
        return layer(input)

    @staticmethod
    def data_norm(input, act=None, epsilon: float = 1e-5, param_attr=None,  # noqa: A002
                  name=None, **kw):
        """Reference data_norm: normalize by GLOBAL running statistics
        (batch_sum/batch_square_sum/batch_size accumulators updated per
        call — never the current batch's own stats)."""
        import jax.numpy as jnp
        from ..nn import functional as F

        x = jnp.asarray(input)
        c = x.shape[1]
        axes = tuple(i for i in range(x.ndim) if i != 1)

        class _DataNorm:
            def __init__(self):
                self.size = jnp.full((c,), 1e4)         # reference init
                self.sum = jnp.zeros((c,))
                self.square_sum = jnp.full((c,), 1e4)

        st_ = nn._layer("data_norm", name, _DataNorm)
        n_new = x.size // c
        st_.size = st_.size + n_new
        st_.sum = st_.sum + jnp.sum(x, axis=axes)
        st_.square_sum = st_.square_sum + jnp.sum(jnp.square(x), axis=axes)
        mean = st_.sum / st_.size
        var = st_.square_sum / st_.size - jnp.square(mean)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = (x - mean.reshape(shape)) / jnp.sqrt(
            var.reshape(shape) + epsilon)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def prelu(x, mode: str = "all", param_attr=None, name=None):
        from ..nn.layers import PReLU

        num = 1 if mode == "all" else x.shape[1]
        layer = nn._layer("prelu", name, lambda: PReLU(
            num_parameters=num, weight_attr=param_attr))
        return layer(x)

    @staticmethod
    def spectral_norm(weight, dim: int = 0, power_iters: int = 1,
                      eps: float = 1e-12, name=None):
        from ..nn.layers import SpectralNorm

        layer = nn._layer("spectral_norm", name, lambda: SpectralNorm(
            list(weight.shape), dim=dim, power_iters=power_iters,
            epsilon=eps))
        return layer(weight)

    @staticmethod
    def bilinear_tensor_product(x, y, size: int, act=None, name=None,
                                param_attr=None, bias_attr=None):
        from ..nn import functional as F
        from ..nn.layers_ext import Bilinear

        layer = nn._layer("bilinear_tensor_product", name, lambda: Bilinear(
            x.shape[-1], y.shape[-1], size, weight_attr=param_attr,
            bias_attr=bias_attr))
        out = layer(x, y)
        return getattr(F, act)(out) if act else out

    @staticmethod
    def row_conv(input, future_context_size: int, param_attr=None,  # noqa: A002
                 act=None):
        """Lookahead row convolution (reference row_conv_op): each step
        mixes the next ``future_context_size`` steps per feature."""
        from .. import create_parameter
        from ..nn import functional as F
        import jax.numpy as jnp

        x = jnp.asarray(input)                    # (B, T, D)
        d = x.shape[-1]
        k = future_context_size + 1
        w = nn._layer("row_conv", None, lambda: create_parameter(
            [k, d], "float32", attr=param_attr))
        pad = jnp.pad(x, ((0, 0), (0, future_context_size), (0, 0)))
        out = sum(pad[:, i:i + x.shape[1], :] * w.value[i][None, None, :]
                  for i in range(k))
        return getattr(F, act)(out) if act else out

    @staticmethod
    def nce(input, label, num_total_classes: int, num_neg_samples: int = 10,  # noqa: A002
            param_attr=None, bias_attr=None, name=None, sample_weight=None,
            sampler: str = "uniform", custom_dist=None, seed: int = 0,
            is_sparse: bool = False):
        """Noise-contrastive estimation loss (reference nce_op): one
        positive + k uniform negatives per row, logistic losses."""
        from .. import create_parameter
        import jax
        import jax.numpy as jnp
        from ..framework import random as fw_random

        x = jnp.asarray(input)                    # (B, D)
        y = jnp.asarray(label).reshape(-1)        # (B,)
        d = x.shape[-1]

        def build():
            w = create_parameter([num_total_classes, d], "float32",
                                 attr=param_attr)
            b = create_parameter([num_total_classes], "float32",
                                 is_bias=True, attr=bias_attr)
            return (w, b)

        w, b = nn._layer("nce", name, build)
        key = fw_random.op_key()
        neg = jax.random.randint(key, (x.shape[0], num_neg_samples), 0,
                                 num_total_classes)
        pos_logit = jnp.einsum("bd,bd->b", x, w.value[y]) + b.value[y]
        neg_logit = jnp.einsum("bd,bkd->bk", x, w.value[neg]) \
            + b.value[neg]
        loss = -jax.nn.log_sigmoid(pos_logit) \
            - jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=1)
        return loss[:, None]

    @staticmethod
    def sparse_embedding(input, size, padding_idx=None, param_attr=None,  # noqa: A002
                         is_test: bool = False, name=None, **kw):
        """Reference sparse_embedding: the PS distributed lookup table —
        here a plain embedding (SURVEY A11: no parameter server; the
        lookup semantics are identical)."""
        return nn.embedding(input, size, padding_idx=padding_idx,
                            param_attr=param_attr, name=name)

    @staticmethod
    def crf_decoding(input, param_attr=None, label=None, length=None,  # noqa: A002
                     name=None):
        """Viterbi decode with a program-owned transition matrix
        (reference crf_decoding op; the text.viterbi_decode engine)."""
        from .. import create_parameter
        from ..text import viterbi_decode
        import jax.numpy as jnp

        x = jnp.asarray(input)
        n = x.shape[-1]
        trans = nn._layer("crf_decoding", name, lambda: create_parameter(
            [n + 2, n], "float32", attr=param_attr))
        # reference layout: rows 0/1 of the (n+2, n) matrix are start/stop
        # scores; here map onto the BOS/EOS convention of viterbi_decode
        full = jnp.zeros((n + 2, n + 2), jnp.float32)
        full = full.at[:n, :n].set(trans.value[2:])
        full = full.at[n, :n].set(trans.value[0])      # BOS row
        full = full.at[:n, n + 1].set(trans.value[1])  # EOS column
        scores, path = viterbi_decode(
            jnp.pad(x, ((0, 0), (0, 0), (0, 2)), constant_values=-1e4),
            full, lengths=length, include_bos_eos_tag=True)
        return path

    # -- control flow (reference static/nn/control_flow.py): direct lax --
    @staticmethod
    def cond(pred, true_fn=None, false_fn=None, name=None):
        import jax

        return jax.lax.cond(pred, true_fn or (lambda: None),
                            false_fn or (lambda: None))

    @staticmethod
    def while_loop(cond, body, loop_vars, is_test: bool = False, name=None):
        import jax

        out = jax.lax.while_loop(lambda vs: cond(*vs),
                                 lambda vs: tuple(body(*vs)),
                                 tuple(loop_vars))
        return list(out)

    @staticmethod
    def case(pred_fn_pairs, default=None, name=None):
        """First-true-wins dispatch (reference control_flow.case).
        Predicates may be traced; all branches must return matching
        structures (the lax.cond contract)."""
        import jax

        out = default() if default is not None else None
        for pred, fn in reversed(list(pred_fn_pairs)):
            prev = out
            if prev is None:
                out = fn()
            else:
                out = jax.lax.cond(pred, fn, lambda p=prev: p)
        return out

    @staticmethod
    def switch_case(branch_index, branch_fns, default=None, name=None):
        import jax

        import jax.numpy as jnp

        if isinstance(branch_fns, dict):
            keys = sorted(branch_fns)
            fns = [branch_fns[k] for k in keys]
            bi = jnp.asarray(branch_index)
            karr = jnp.asarray(keys)
            hit = bi == karr
            # EXACT key match; anything else runs the default (reference
            # semantics) — or the last branch if none was given
            match = jnp.sum(jnp.where(hit, jnp.arange(len(keys)), 0))
            if default is not None:
                fns = fns + [default]
                idx = jnp.where(jnp.any(hit), match, len(keys))
            else:
                idx = jnp.where(jnp.any(hit), match, len(keys) - 1)
        else:
            fns = list(branch_fns)
            if default is not None:
                fns = fns + [default]
            idx = jnp.clip(jnp.asarray(branch_index), 0, len(fns) - 1)
        return jax.lax.switch(idx, fns)

    @staticmethod
    def py_func(func, x, out, backward_func=None,
                skip_vars_in_backward_input=None):
        return py_func(func, x, out, backward_func,
                       skip_vars_in_backward_input)

    # -- LoD sequence family (reference static/nn/sequence_lod.py).  The
    # -- TPU rendering of LoD: a PADDED batch plus a lengths vector (the
    # -- sequence_mask convention); every op documents that contract. ----
    @staticmethod
    def _mask(x, length):
        import jax.numpy as jnp

        T = x.shape[1]
        return (jnp.arange(T)[None, :] < jnp.asarray(length)[:, None])

    @staticmethod
    def sequence_softmax(input, length=None, use_cudnn=False, name=None):  # noqa: A002
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(input)                    # (B, T)
        if length is None:
            return jax.nn.softmax(x, axis=1)
        m = nn._mask(x, length)
        return jax.nn.softmax(jnp.where(m, x, -1e30), axis=1) * m

    @staticmethod
    def sequence_pool(input, pool_type: str, length=None, is_test=False,  # noqa: A002
                      pad_value: float = 0.0):
        import jax.numpy as jnp

        x = jnp.asarray(input)                    # (B, T, D) or (B, T)
        if length is None:
            length = jnp.full((x.shape[0],), x.shape[1])
        m = nn._mask(x, length)
        while m.ndim < x.ndim:
            m = m[..., None]
        cnt = jnp.maximum(jnp.asarray(length), 1).astype(x.dtype)
        shaped = cnt.reshape((-1,) + (1,) * (x.ndim - 2))
        pt = pool_type.lower()
        if pt == "sum":
            out = jnp.sum(jnp.where(m, x, 0), axis=1)
        elif pt == "average":
            out = jnp.sum(jnp.where(m, x, 0), axis=1) / shaped
        elif pt == "sqrt":
            out = jnp.sum(jnp.where(m, x, 0), axis=1) / jnp.sqrt(shaped)
        elif pt == "max":
            out = jnp.max(jnp.where(m, x, -jnp.inf), axis=1)
        elif pt == "last":
            idx = (jnp.asarray(length) - 1).astype(jnp.int32)
            out = jnp.take_along_axis(
                x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
            ).squeeze(1)
        elif pt == "first":
            out = x[:, 0]
        else:
            enforce(False, f"unknown pool_type {pool_type!r}")
        empty = (jnp.asarray(length) == 0).reshape(
            (-1,) + (1,) * (out.ndim - 1))
        return jnp.where(empty, pad_value, out)

    @staticmethod
    def sequence_first_step(input, length=None):  # noqa: A002
        return nn.sequence_pool(input, "first", length)

    @staticmethod
    def sequence_last_step(input, length=None):  # noqa: A002
        return nn.sequence_pool(input, "last", length)

    @staticmethod
    def sequence_conv(input, num_filters: int, filter_size: int = 3,  # noqa: A002
                      filter_stride: int = 1, padding: bool = True,
                      padding_start=None, param_attr=None, bias_attr=None,
                      act=None, name=None):
        """Context-window convolution over the time axis (reference
        sequence_conv_op): ``filter_size`` steps starting at
        ``padding_start`` (default -(size-1)//2) feed one projection."""
        from .. import create_parameter
        from ..nn import functional as F
        import jax.numpy as jnp

        x = jnp.asarray(input)                    # (B, T, D)
        d = x.shape[-1]
        start = padding_start if padding_start is not None \
            else -((filter_size - 1) // 2)

        def build():
            w = create_parameter([filter_size * d, num_filters], "float32",
                                 attr=param_attr)
            b = None if bias_attr is False else create_parameter(
                [num_filters], "float32", is_bias=True, attr=bias_attr)
            return (w, b)

        w, b = nn._layer("sequence_conv", name, build)
        ctx = []
        T = x.shape[1]
        for i in range(filter_size):
            off = start + i
            sl = jnp.roll(x, -off, axis=1)
            idx = jnp.arange(T) + off
            valid = ((idx >= 0) & (idx < T))[None, :, None]
            ctx.append(jnp.where(valid, sl, 0))
        ctx = jnp.concatenate(ctx, axis=-1)       # (B, T, k*D)
        out = ctx @ w.value
        if b is not None:
            out = out + b.value
        return getattr(F, act)(out) if act else out

    @staticmethod
    def sequence_concat(input, name=None):  # noqa: A002
        import jax.numpy as jnp

        return jnp.concatenate([jnp.asarray(x) for x in input], axis=1)

    @staticmethod
    def sequence_slice(input, offset, length, name=None):  # noqa: A002
        """Per-row slice [offset, offset+length) along time (reference
        sequence_slice_op); ``length`` must be uniform (static shapes)."""
        import jax.numpy as jnp

        x = jnp.asarray(input)
        off = jnp.asarray(offset).reshape(-1)
        ln = jnp.asarray(length).reshape(-1)
        L = int(ln[0])
        idx = off[:, None] + jnp.arange(L)[None, :]
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(
                jnp.int32), axis=1)

    @staticmethod
    def sequence_expand(x, y, ref_level: int = -1, name=None):
        """Tile each row of x ``n`` times where n comes from y's lengths
        (reference sequence_expand; uniform repeat under static shapes)."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        n = jnp.asarray(y).shape[1] if hasattr(y, "shape") else int(y)
        return jnp.repeat(x, n, axis=0)

    @staticmethod
    def sequence_expand_as(x, y, name=None):
        import jax.numpy as jnp

        x = jnp.asarray(x)
        reps = jnp.asarray(y).shape[0] // x.shape[0]
        return jnp.repeat(x, reps, axis=0)

    @staticmethod
    def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
        """Pad a (B, T, ...) batch out to ``maxlen`` steps; returns
        (padded, lengths) like the reference."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        T = x.shape[1]
        if length is None:
            length = jnp.full((x.shape[0],), T, jnp.int32)
        tgt = maxlen or T
        pad = [(0, 0), (0, max(0, tgt - T))] + [(0, 0)] * (x.ndim - 2)
        out = jnp.pad(x, pad, constant_values=pad_value)[:, :tgt]
        m = nn._mask(out, length)
        while m.ndim < out.ndim:
            m = m[..., None]
        out = jnp.where(m, out, pad_value)
        return out, jnp.asarray(length)

    @staticmethod
    def sequence_unpad(x, length, name=None):
        """Trim to the max real length and zero the padding (the padded-
        batch rendering of unpad; per-row ragged output needs host
        lists)."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        m = nn._mask(x, length)
        while m.ndim < x.ndim:
            m = m[..., None]
        return jnp.where(m, x, 0)

    @staticmethod
    def sequence_reshape(input, new_dim: int, name=None):  # noqa: A002
        import jax.numpy as jnp

        x = jnp.asarray(input)
        return x.reshape(x.shape[0], -1, new_dim)

    @staticmethod
    def sequence_reverse(x, length=None, name=None):
        """Reverse each row's REAL prefix, keeping padding in place
        (reference sequence_reverse_op)."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        T = x.shape[1]
        if length is None:
            return jnp.flip(x, axis=1)
        ln = jnp.asarray(length).reshape(-1, 1)
        t = jnp.arange(T)[None, :]
        src = jnp.where(t < ln, ln - 1 - t, t).astype(jnp.int32)
        return jnp.take_along_axis(
            x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)

    @staticmethod
    def sequence_scatter(input, index, updates, name=None):  # noqa: A002
        import jax.numpy as jnp

        x = jnp.asarray(input)
        idx = jnp.asarray(index)
        upd = jnp.asarray(updates)
        b = jnp.arange(x.shape[0])[:, None] * jnp.ones_like(idx)
        return x.at[b, idx].add(upd)

    @staticmethod
    def sequence_enumerate(input, win_size: int, pad_value: int = 0,  # noqa: A002
                           name=None):
        """Sliding windows of ids (reference sequence_enumerate_op):
        (B, T) → (B, T, win_size), tail windows padded."""
        import jax.numpy as jnp

        x = jnp.asarray(input)
        T = x.shape[1]
        cols = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]
        valid = cols < T
        g = jnp.take(x, jnp.minimum(cols, T - 1), axis=1)
        return jnp.where(valid[None], g, pad_value)

    @staticmethod
    def multi_box_head(inputs, image, num_classes: int, base_size=None,
                       aspect_ratios=None, min_ratio=None, max_ratio=None,
                       min_sizes=None, max_sizes=None, **kw):
        """SSD multi-box head (reference multi_box_head): per-feature-map
        conv heads predicting box deltas + class scores over generated
        prior boxes.  Minimal faithful rendering: one 3x3 conv pair per
        input map; priors on the map's grid."""
        from ..nn.layers import Conv2D
        import jax.numpy as jnp

        aspect_ratios = aspect_ratios or [[1.0]] * len(inputs)
        locs, confs, boxes = [], [], []
        for i, feat in enumerate(inputs):
            pr = len(aspect_ratios[i]) + 1
            c = feat.shape[1]
            loc_l = nn._layer(f"mbox_loc_{i}", None, lambda c=c, pr=pr:
                              Conv2D(c, pr * 4, 3, padding=1))
            conf_l = nn._layer(f"mbox_conf_{i}", None,
                               lambda c=c, pr=pr: Conv2D(
                                   c, pr * num_classes, 3, padding=1))
            n, _, h, w = feat.shape
            locs.append(jnp.transpose(loc_l(feat), (0, 2, 3, 1)
                                      ).reshape(n, -1, 4))
            confs.append(jnp.transpose(conf_l(feat), (0, 2, 3, 1)
                                       ).reshape(n, -1, num_classes))
            ys, xs = jnp.meshgrid(
                (jnp.arange(h) + 0.5) / h, (jnp.arange(w) + 0.5) / w,
                indexing="ij")
            s = 1.0 / (2 ** i * 2)
            # LOCATION-major, prior-minor — the same (cell, prior) order
            # the NHWC-reshaped conv heads emit, so locs[i] pairs with
            # prior[i]
            per_cell = []
            for r in [1.0] + list(aspect_ratios[i]):
                bw, bh = s * (r ** 0.5), s / (r ** 0.5)
                per_cell.append(jnp.stack(
                    [xs - bw / 2, ys - bh / 2, xs + bw / 2, ys + bh / 2],
                    axis=-1))                      # (h, w, 4)
            boxes.append(jnp.stack(per_cell, axis=2).reshape(-1, 4))
        prior = jnp.concatenate(boxes, axis=0)
        var = jnp.broadcast_to(jnp.asarray([0.1, 0.1, 0.2, 0.2]),
                               prior.shape)
        return (jnp.concatenate(locs, axis=1),
                jnp.concatenate(confs, axis=1), prior, var)



# ---------------------------------------------------------------------------
# Static long-tail surface (reference static/__init__.py __all__ parity).
# The stance is unchanged (module docstring): Program is a scope around one
# traced callable.  Real capabilities (EMA, state save/load, scopes,
# py_func/Print, places) are implemented; pre-2.0 graph-surgery entry
# points (append_backward/gradients) raise with the functional recipe.
# ---------------------------------------------------------------------------
import contextlib as _contextlib

Variable = InputSpec      # the declared-tensor role in this facade


def name_scope(prefix: str = None):
    """Reference static.name_scope: a name prefix for ops — naming only
    in the one-jit design; kept as a context manager for ported code."""
    return _contextlib.nullcontext(prefix)


def device_guard(device: str = None):
    """Reference static.device_guard: op placement hint.  XLA owns
    placement; the guard is accepted and ignored (documented)."""
    return _contextlib.nullcontext(device)


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


@_contextlib.contextmanager
def scope_guard(scope: _Scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield scope
    finally:
        _global_scope = prev


def cpu_places(device_count: Optional[int] = None):
    from ..framework import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..framework import TPUPlace
    import jax as _jax
    ids = device_ids if device_ids is not None \
        else range(len(_jax.devices()))
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places
npu_places = cuda_places
mlu_places = cuda_places


def create_global_var(shape, value, dtype, persistable: bool = False,
                      force_cpu: bool = False, name=None):
    """A named global tensor in the current scope (reference
    create_global_var)."""
    from ..framework.dtype import convert_dtype
    v = jnp.full(tuple(shape), value, convert_dtype(dtype))
    _global_scope[name or f"gvar_{len(_global_scope)}"] = v
    return v


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .. import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def Print(input, first_n: int = -1, message: Optional[str] = None,  # noqa: A002
          summarize: int = 20, print_tensor_name: bool = True, **kw):
    """Reference static.Print op: print a tensor during execution —
    jax.debug.print works inside jit (the op's role)."""
    jax.debug.print((message or "") + " {x}", x=input)
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static.py_func: host-python op in the graph — the
    pure_callback bridge (utils/cpp_extension.py host-op machinery)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    shape_dtype = jax.tree_util.tree_map(
        lambda o: jax.ShapeDtypeStruct(tuple(o.shape), o.dtype), out)
    return jax.pure_callback(func, shape_dtype, *xs)


def accuracy(input, label, k: int = 1, **kw):  # noqa: A002
    """Top-k accuracy op (reference static.accuracy)."""
    topk = jnp.argsort(jnp.asarray(input), axis=-1)[..., -k:]
    lbl = jnp.asarray(label).reshape(-1, 1)
    return jnp.mean(jnp.any(topk == lbl, axis=-1).astype(jnp.float32))


def auc(input, label, curve: str = "ROC", num_thresholds: int = 4095, **kw):  # noqa: A002
    """Streaming-free AUC op over one batch (reference static.auc)."""
    from ..metric import Auc
    m = Auc(num_thresholds=num_thresholds)
    m.update(jnp.asarray(input), jnp.asarray(label))
    return jnp.asarray(m.accumulate(), jnp.float32)


class ExponentialMovingAverage:
    """Reference static.ExponentialMovingAverage: shadow parameters
    ema = decay*ema + (1-decay)*param with bias correction; apply()
    temporarily swaps shadows in (restore() swaps back).  Functional
    form: ``update(params)`` returns None (state held here);
    ``shadow()`` returns the corrected averages."""

    def __init__(self, decay: float = 0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = None
        self._step = 0
        self._backup = None

    def update(self, params):
        params = {k: jnp.asarray(v) for k, v in params.items()}
        if self._ema is None:
            self._ema = {k: jnp.zeros_like(v) for k, v in params.items()}
        d = self._decay
        self._ema = {k: d * self._ema[k] + (1 - d) * params[k]
                     for k in params}
        self._step += 1

    def shadow(self):
        enforce(self._ema is not None, "EMA.update never called")
        corr = 1 - self._decay ** self._step
        return {k: v / corr for k, v in self._ema.items()}

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        yield self.shadow()

    def restore(self, executor=None):
        pass


class WeightNormParamAttr:
    """Reference static.WeightNormParamAttr: ParamAttr requesting weight
    normalization — the dygraph path implements it via
    nn.utils.weight_norm hooks; this records dim + the attr fields."""

    def __init__(self, dim=None, name=None, initializer=None, trainable=True,
                 **kw):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


class BuildStrategy:
    """Graph-pass configuration (reference BuildStrategy).  XLA owns the
    pass pipeline; the knobs are recorded so ported scripts construct and
    set them freely (documented no-ops)."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        return self.__dict__.get("_opts", {}).get(k, False)


class ExecutionStrategy(BuildStrategy):
    pass


class CompiledProgram:
    """Reference CompiledProgram(program).with_data_parallel(...): the
    one-XLA-compilation design makes this a pass-through wrapper whose
    run delegates to the wrapped Program (GSPMD covers data parallel)."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self

    def run(self, feed):
        return self._program.run(feed)


class ParallelExecutor(CompiledProgram):
    def __init__(self, use_cuda: bool = False, loss_name=None,
                 main_program=None, build_strategy=None,
                 exec_strategy=None, scope=None, share_vars_from=None):
        super().__init__(main_program or default_main_program())


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Pre-2.0 graph surgery (reference append_backward): inserting grad
    ops into a ProgramDesc has no analog when jax.grad IS the backward.
    Raises with the functional recipe (docs/MIGRATION.md: static)."""
    raise NotImplementedError(
        "append_backward rewrites a ProgramDesc; in this runtime the "
        "backward is jax.value_and_grad over the program's python "
        "function — build the train step functionally "
        "(docs/MIGRATION.md: 'static graphs').")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    raise NotImplementedError(
        "static.gradients rewrites a ProgramDesc; use "
        "paddle_tpu.autograd.grad / jax.grad over a function of the "
        "inputs (docs/MIGRATION.md: 'static graphs').")


# --- program/persistables serialization (delegates to the jit/io stack) --
def save(program: Program, model_path: str, protocol: int = 4):
    """Persist the scope's variables for a Program (reference
    static.save): parameters live in the program's nn layer store."""
    from ..framework.io import save as _save
    state = {k: getattr(l, "state_dict", lambda: {})()
             for k, l in program._nn_layers.items()}
    _save(state, model_path + ".pdparams")


def load(program: Program, model_path: str, executor=None, var_list=None):
    from ..framework.io import load as _load
    state = _load(model_path + ".pdparams")
    for k, sub in state.items():
        if k in program._nn_layers and hasattr(program._nn_layers[k],
                                               "set_state_dict"):
            program._nn_layers[k].set_state_dict(sub)
    return state


def serialize_program(feed_vars, fetch_vars, **kwargs) -> bytes:
    import pickle
    return pickle.dumps({"feed": [getattr(v, "name", None) for v in feed_vars],
                         "fetch": [getattr(v, "name", None) for v in fetch_vars]})


def deserialize_program(data: bytes):
    import pickle
    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, executor=None) -> bytes:
    import pickle
    prog = default_main_program()
    state = {k: getattr(l, "state_dict", lambda: {})()
             for k, l in prog._nn_layers.items()}
    state = jax.tree_util.tree_map(lambda x: np.asarray(x), state)
    return pickle.dumps(state)


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle
    state = pickle.loads(data)
    for k, sub in state.items():
        if k in program._nn_layers and hasattr(program._nn_layers[k],
                                               "set_state_dict"):
            program._nn_layers[k].set_state_dict(sub)
    return state


def save_to_file(path: str, content: bytes):
    from ..utils import fsio
    fsio.write_bytes(path, content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    return program


def load_program_state(model_path: str, var_list=None):
    from ..framework.io import load as _load
    return _load(model_path + ".pdparams")


def set_program_state(program, state_dict):
    for k, sub in state_dict.items():
        if k in program._nn_layers and hasattr(program._nn_layers[k],
                                               "set_state_dict"):
            program._nn_layers[k].set_state_dict(sub)


class IpuStrategy:       # IPU backends have no TPU counterpart; config
    def __init__(self):  # shells keep ported scripts importable (N/A in
        self._opts = {}  # docs/MIGRATION.md)

    def set_graph_config(self, **kw):
        self._opts.update(kw)


class IpuCompiledProgram(CompiledProgram):
    pass


def ipu_shard_guard(index: int = -1, stage: int = -1):
    return _contextlib.nullcontext()


__all__ += ["Variable", "name_scope", "device_guard", "global_scope",
            "scope_guard", "cpu_places", "cuda_places", "xpu_places",
            "npu_places", "mlu_places", "create_global_var",
            "create_parameter", "Print", "py_func", "accuracy", "auc",
            "ExponentialMovingAverage", "WeightNormParamAttr",
            "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
            "ParallelExecutor", "append_backward", "gradients", "save",
            "load", "serialize_program", "deserialize_program",
            "serialize_persistables", "deserialize_persistables",
            "save_to_file", "load_from_file", "normalize_program",
            "load_program_state", "set_program_state", "IpuStrategy",
            "IpuCompiledProgram", "ipu_shard_guard"]
