"""paddle.static migration facade (L7 static-graph surface).

The reference maintains a whole second programming model — Program/Block
IR, append_backward, four executors (SURVEY C19-C23).  This framework
deliberately has ONE codepath: a jitted function IS the static program
(SURVEY A13 records the justification).  This module keeps the static
API's *shape* so ported scripts have landing points, with each symbol
mapped to its one-codepath equivalent:

- ``static.data`` / ``InputSpec``  → trace-time specs (feed declarations)
- ``Program`` / ``program_guard`` / ``default_main_program`` → a Program
  here is just a named scope holding a traced callable; building ops
  imperatively inside the guard is not supported (write a function and
  ``jit`` it — that's the static graph)
- ``Executor.run(program, feed, fetch_list)`` → calls the program's
  callable under jit with the feed dict
- ``save_inference_model`` / ``load_inference_model`` → the jit.save /
  jit.load StableHLO artifact
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..jit import InputSpec

__all__ = ["InputSpec", "data", "Program", "program_guard",
           "default_main_program", "default_startup_program", "Executor",
           "save_inference_model", "load_inference_model", "nn"]


def data(name: str, shape: Sequence[Optional[int]], dtype="float32"):
    """Feed declaration (reference static.data) → InputSpec."""
    return InputSpec(shape, dtype=dtype, name=name)


class Program:
    """A named scope for one traced callable (the one-codepath rendering of
    ProgramDesc).  Set the callable with ``set_fn`` (signature
    ``fn(**feed) -> output or dict``); Executor.run jits and runs it."""

    def __init__(self, name: str = "main"):
        self.name = name
        self._fn: Optional[Callable] = None
        self._jitted = None
        # static.nn parameter store: layers created by the nn helpers are
        # cached per program by deterministic build order, so a retrace
        # (new batch shape) reuses the SAME weights instead of redrawing
        self._nn_layers: Dict[str, Any] = {}
        self._nn_counters: Dict[str, int] = {}

    def _nn_slot(self, kind: str, name: Optional[str]) -> str:
        if name:
            return name
        idx = self._nn_counters.get(kind, 0)
        self._nn_counters[kind] = idx + 1
        return f"{kind}_{idx}"

    def set_fn(self, fn: Callable) -> "Program":
        self._fn = fn

        def _traced(feed):
            # reset build-order counters so every (re)trace walks the
            # helpers in the same deterministic sequence
            self._nn_counters.clear()
            with program_guard(self):
                return fn(**feed)

        self._jitted = jax.jit(_traced)
        return self

    def run(self, feed: Dict[str, Any]):
        enforce(self._fn is not None,
                f"Program {self.name!r} has no function attached — build "
                "static programs as python functions (Program.set_fn) and "
                "jit compiles them; imperative op-building has no analog")
        return self._jitted({k: jnp.asarray(np.asarray(v))
                             for k, v in feed.items()})

    def clone(self, for_test: bool = False) -> "Program":
        p = Program(self.name)
        p._fn, p._jitted = self._fn, self._jitted
        return p


_default_main = Program("main")
_default_startup = Program("startup")


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    """Source-compat scope: temporarily makes ``main_program`` the default.
    (Params initialize at Layer construction, so startup programs carry
    nothing here.)"""
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


class Executor:
    """Reference static.Executor facade: ``run`` executes a Program's
    callable; place selection is owned by jax (the device the arrays live
    on), kept as an argument for source compat."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[List] = None, return_numpy: bool = True):
        program = program or default_main_program()
        out = program.run(feed or {})
        if isinstance(out, dict):
            keys = fetch_list or list(out.keys())
            outs = [out[k] for k in keys]
        elif isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return list(outs)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         *, layer=None, input_spec=None, **kw):
    """→ jit.save (the StableHLO artifact).  Pass the Layer via ``layer``
    (the Program-IR route has no analog)."""
    from .. import jit as pt_jit
    enforce(layer is not None,
            "save_inference_model on TPU exports a Layer: pass layer=<Layer>"
            " and input_spec=[InputSpec...] (≙ jit.save)")
    specs = input_spec if input_spec is not None else feed_vars
    enforce(specs is not None,
            "save_inference_model needs input specs: pass "
            "input_spec=[InputSpec...] (or feed_vars from static.data)")
    pt_jit.save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None):
    from .. import jit as pt_jit
    loaded = pt_jit.load(path_prefix)
    feed_names = [s.name or f"input_{i}"
                  for i, s in enumerate(loaded.input_spec)]
    return loaded, feed_names, None


class nn:
    """paddle.static.nn source-compat namespace (reference static/nn/
    common.py fc, input.py embedding, ...).

    Helpers cache their layers on the current default Program keyed by
    build order (or explicit ``name``), with weights materialized at
    compile time (``jax.ensure_compile_time_eval``) — a jit retrace
    reuses the same parameters, matching the reference's
    program-owns-the-parameters model."""

    @staticmethod
    def _layer(kind, name, build):
        prog = default_main_program()
        slot = prog._nn_slot(kind, name)
        if slot not in prog._nn_layers:
            with jax.ensure_compile_time_eval():
                prog._nn_layers[slot] = build()
        return prog._nn_layers[slot]

    @staticmethod
    def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
           bias_attr=None, activation=None, name=None):
        """Reference signature order (static/nn/common.py fc)."""
        from ..nn import functional as F
        from ..nn.layers import Linear
        import jax.numpy as jnp

        x = jnp.asarray(x)
        lead = x.shape[:num_flatten_dims]
        flat = x.reshape(*lead, -1)
        layer = nn._layer("fc", name, lambda: Linear(
            flat.shape[-1], size, weight_attr=weight_attr,
            bias_attr=bias_attr))
        out = layer(flat)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse: bool = False, padding_idx=None,
                  param_attr=None, dtype="float32", name=None):
        from ..nn.layers import Embedding

        layer = nn._layer("embedding", name, lambda: Embedding(
            size[0], size[1], padding_idx=padding_idx,
            weight_attr=param_attr, dtype=dtype))
        return layer(input)

    @staticmethod
    def batch_norm(input, act=None, momentum: float = 0.9,
                   epsilon: float = 1e-5, data_layout: str = "NCHW",
                   name=None, **kw):
        from ..nn import functional as F
        from ..nn.layers import BatchNorm2D

        enforce(not kw, f"batch_norm got unsupported kwargs {sorted(kw)}")
        features = input.shape[1] if data_layout == "NCHW" \
            else input.shape[-1]
        layer = nn._layer("batch_norm", name, lambda: BatchNorm2D(
            features, momentum=momentum, epsilon=epsilon,
            data_format=data_layout))
        out = layer(input)
        return getattr(F, act)(out) if act else out
