"""Optimizers (reference: python/paddle/optimizer/*.py and the fused CUDA
optimizer ops in paddle/fluid/operators/optimizers/).

Design: each optimizer has a **functional core** —

    state              = opt.init(params)          # pytree of slots
    new_params, state  = opt.apply_gradients(grads, params, state)

that is pure and jit/pjit/shard_map-safe: under GSPMD, sharding the params
pytree automatically shards the slot pytrees the same way, which is how the
reference's ZeRO-1 optimizer-state sharding (DygraphShardingOptimizer,
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:28)
falls out for free on TPU (see SURVEY.md A3).

A stateful wrapper (``opt.step(grads)``) gives dygraph-style ergonomics over a
bound Parameter list.  Master-weight (fp32) support mirrors the reference's
multi_precision attr on adam/momentum ops.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..nn.layer import Parameter
from . import lr as lr  # noqa: F401  (paddle.optimizer.lr namespace)
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "RMSProp", "Adam", "AdamW",
    "Lamb", "AdamMax", "lr", "ClipGradByValue", "ClipGradByNorm",
    "ClipGradByGlobalNorm", "global_norm",
]


# ---------------------------------------------------------------------------
# Gradient clipping (reference: python/paddle/fluid/clip.py; the distributed
# cross-group variant lives in paddle_tpu/distributed/fleet/optimizer.py)
# ---------------------------------------------------------------------------
def global_norm(grads) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


class ClipGradByValue:
    def __init__(self, max: float, min: Optional[float] = None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm:
    def __init__(self, clip_norm: float):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def _clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return jax.tree_util.tree_map(_clip, grads)


class ClipGradByGlobalNorm:
    """Reference: fluid/clip.py ClipGradByGlobalNorm.  Under pjit the sum of
    squares is computed on sharded grads and XLA inserts the cross-device
    reductions — no explicit communication needed (unlike the reference's
    HybridParallelClipGrad which allreduces per group)."""

    def __init__(self, clip_norm: float = 1.0):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        norm = global_norm(grads)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


# ---------------------------------------------------------------------------
# Base optimizer
# ---------------------------------------------------------------------------
def _is_float_param(p) -> bool:
    return jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)


class Optimizer:
    """Base class. Subclasses implement ``_init_slot(p)`` and
    ``_update(g, p, slots, lr, step)`` operating on single fp32 leaves."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision: bool = True,
                 apply_decay_param_fun: Optional[Callable[[str], bool]] = None):
        self._lr = learning_rate
        self._grad_clip = grad_clip
        # weight_decay: float (L2 semantics) or a regularizer instance
        # (reference: optimizer accepts paddle.regularizer.L1Decay/L2Decay)
        from ..regularizer import L1Decay, L2Decay
        self._l1 = 0.0
        if isinstance(weight_decay, L1Decay):
            self._wd, self._l1 = 0.0, weight_decay.coeff
        elif isinstance(weight_decay, L2Decay):
            self._wd = weight_decay.coeff
        else:
            self._wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun
        self.multi_precision = multi_precision
        self._parameters = list(parameters) if parameters is not None else None
        self._state = None  # lazily built for the stateful path

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr.get_lr()
        return self._lr

    def set_lr(self, value: float):
        enforce(not isinstance(self._lr, LRScheduler),
                "can't set_lr when using an LRScheduler")
        self._lr = value

    def _lr_at(self, step):
        if isinstance(self._lr, LRScheduler):
            return self._lr(step)
        return jnp.asarray(self._lr, jnp.float32)

    # -- functional API ----------------------------------------------------
    def init(self, params) -> Dict[str, Any]:
        """Build the slot-variable pytree for a params pytree."""
        def _master(p):
            if self.multi_precision and _is_float_param(p) and \
                    jnp.asarray(p).dtype != jnp.float32:
                return jnp.asarray(p).astype(jnp.float32)
            return None
        slots = jax.tree_util.tree_map(self._init_slot, params)
        master = jax.tree_util.tree_map(_master, params)
        return {"step": jnp.zeros((), jnp.int32), "slots": slots,
                "master": master}

    def apply_gradients(self, grads, params, state, lr=None):
        """Pure update: returns (new_params, new_state).

        ``lr`` overrides the schedule (used by the stateful path, where the
        paddle convention is that the user drives the scheduler's .step() —
        typically per epoch — rather than the optimizer's iteration count)."""
        step = state["step"] + 1
        lr_t = jnp.asarray(lr, jnp.float32) if lr is not None \
            else self._lr_at(step - 1)
        if self._grad_clip is not None:
            grads = self._grad_clip(grads)

        # decide weight decay per-leaf using the key path (dict pytrees keep
        # param names, so apply_decay_param_fun gets real names)
        wd_tree = self._decay_tree(params)

        def _upd(g, p, slots, master, wd, l1):
            if g is None:
                return p, slots, master
            compute_p = master if master is not None else jnp.asarray(p)
            g32 = g.astype(jnp.float32)
            if self._l1:   # L1Decay: lasso penalty as a gradient addition
                g32 = g32 + l1 * jnp.sign(compute_p.astype(jnp.float32))
            new_p32, new_slots = self._update(
                g32, compute_p.astype(jnp.float32), slots, lr_t, step, wd)
            if master is not None:
                return new_p32.astype(jnp.asarray(p).dtype), new_slots, new_p32
            return new_p32.astype(jnp.asarray(p).dtype), new_slots, None

        l1_tree = self._l1_tree(params)
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state["slots"])
        flat_m = treedef.flatten_up_to(state["master"])
        flat_w = treedef.flatten_up_to(wd_tree)
        flat_l1 = treedef.flatten_up_to(l1_tree)
        out = [_upd(g, p, s, m, w, l1) for g, p, s, m, w, l1 in
               zip(flat_g, flat_p, flat_s, flat_m, flat_w, flat_l1)]
        new_params = treedef.unflatten([o[0] for o in out])
        new_slots = treedef.unflatten([o[1] for o in out])
        new_master = treedef.unflatten([o[2] for o in out])
        return new_params, {"step": step, "slots": new_slots,
                            "master": new_master}

    # convenience: one-call pytree update
    def update(self, grads, params, state):
        return self.apply_gradients(grads, params, state)

    def _decay_tree(self, params, coeff=None):
        """Per-leaf decay coefficients (``coeff`` defaults to the L2
        weight decay); apply_decay_param_fun receives the dotted key path
        (real parameter names when params is the state_dict-style dict
        pytree)."""
        coeff = self._wd if coeff is None else coeff
        fn = self._apply_decay_param_fun

        def _path_str(path):
            parts = []
            for k in path:
                if hasattr(k, "key"):
                    parts.append(str(k.key))
                elif hasattr(k, "idx"):
                    parts.append(str(k.idx))
                elif hasattr(k, "name"):
                    parts.append(str(k.name))
            return ".".join(parts)

        return jax.tree_util.tree_map_with_path(
            lambda path, p: coeff if (coeff and (
                fn is None or fn(_path_str(path)))) else 0.0,
            params)

    def _l1_tree(self, params):
        """Per-leaf L1Decay coefficients, gated by the same
        apply_decay_param_fun as L2 decay."""
        return self._decay_tree(params, coeff=self._l1)

    # -- stateful API ------------------------------------------------------
    def _param_keys(self):
        """Stable, unique dict keys carrying real parameter names so
        apply_decay_param_fun / exclude_from_weight_decay_fn see what the
        user's model calls the parameter, not a list index.  Keys are
        snapshotted at first use: name collisions (two models with the same
        architecture) get a #i suffix, and late name assignment can't change
        the pytree structure mid-training."""
        if getattr(self, "_param_key_list", None) is None:
            keys, seen = [], set()
            for i, p in enumerate(self._parameters):
                k = p.name if p.name else f"param_{i}"
                if k in seen:
                    k = f"{k}#{i}"
                seen.add(k)
                keys.append(k)
            self._param_key_list = keys
        return self._param_key_list

    def _ensure_state(self):
        enforce(self._parameters is not None,
                "stateful step() needs parameters= at construction")
        if self._state is None:
            values = dict(zip(self._param_keys(),
                              (p.value for p in self._parameters)))
            self._state = self.init(values)

    def step(self, grads=None):
        """Apply grads (list matching the bound parameters)."""
        self._ensure_state()
        if grads is None:
            grads = [p._grad for p in self._parameters]
        keys = self._param_keys()
        values = dict(zip(keys, (p.value for p in self._parameters)))
        grads = dict(zip(keys, (None if not t.trainable else g
                                for g, t in zip(grads, self._parameters))))
        lr = self.get_lr() if isinstance(self._lr, LRScheduler) else None
        new_values, self._state = self.apply_gradients(
            grads, values, self._state, lr=lr)
        for p, k in zip(self._parameters, keys):
            p.value = new_values[k]
            p._grad = None

    def clear_grad(self):
        if self._parameters:
            for p in self._parameters:
                p._grad = None

    def backward(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None, callbacks=None):
        """Reference Optimizer.backward: compute (param, grad) pairs for
        ``minimize``.  Functionally: grads of ``loss`` — when ``loss`` is
        a CALLABLE of the parameter values it is differentiated directly;
        a plain tensor cannot be walked backward (no tape) and raises
        with the recipe.  Grads are computed for (and later applied to)
        the CONSTRUCTOR-bound parameters; a ``parameters`` argument must
        match that binding — rebinding per call is not supported in the
        stateful path."""
        enforce(self._parameters,
                "optimizer has no bound parameters; construct with "
                "parameters=... (the stateful step/minimize path is "
                "bound at construction)")
        if parameters is not None:
            enforce(list(parameters) == list(self._parameters),
                    "minimize/backward(parameters=...) must match the "
                    "constructor-bound parameter list — per-call "
                    "rebinding is not supported")
        if not callable(loss):
            raise RuntimeError(
                "Optimizer.backward(loss_tensor) needs an autograd tape, "
                "which does not exist here; pass a CALLABLE "
                "loss_fn(values_dict) (or use jax.value_and_grad "
                "directly — docs/MIGRATION.md: autograd).")
        keys = self._param_keys()
        values = dict(zip(keys, (p.value for p in self._parameters)))
        grads = jax.grad(loss)(values)
        return [(p, grads[k]) for p, k in zip(self._parameters, keys)]

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Reference Optimizer.minimize: backward + apply.  ``loss`` is a
        callable of the parameter-values dict (see backward)."""
        pg = self.backward(loss, parameters=parameters)
        self.step([g for _, g in pg])
        return None, pg

    def append_regularization_ops(self, params_grads, regularization=None):
        """Reference append_regularization_ops: add the regularizer's
        gradient term to each grad (decay is otherwise folded into
        _update at apply time)."""
        coeff = getattr(regularization, "coeff", None)
        if coeff is None:
            return params_grads
        from ..regularizer import L1Decay
        if isinstance(regularization, L1Decay):
            return [(p, g + coeff * jnp.sign(jnp.asarray(p)))
                    for p, g in params_grads]
        return [(p, g + coeff * jnp.asarray(p)) for p, g in params_grads]

    def get_opti_var_name_list(self):
        """Slot-variable names (reference get_opti_var_name_list)."""
        self._ensure_state()
        names = []
        for pname, slot in self._state["slots"].items():
            if isinstance(slot, dict):   # slotless optimizers (SGD): None
                names += [f"{pname}.{s}" for s in slot]
        return names

    def state_dict(self):
        self._ensure_state()
        sd = {"state": self._state}
        if isinstance(self._lr, LRScheduler):
            sd["lr"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, sd):
        self._state = sd["state"]
        if isinstance(self._lr, LRScheduler) and "lr" in sd:
            self._lr.set_state_dict(sd["lr"])

    # -- subclass hooks ----------------------------------------------------
    def _init_slot(self, p):
        return ()

    def _update(self, g, p, slots, lr, step, wd):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Concrete rules (fp32 math; reference operators/optimizers/*_op.cc semantics)
# ---------------------------------------------------------------------------
class SGD(Optimizer):
    def _update(self, g, p, slots, lr, step, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, slots


class Momentum(Optimizer):
    """Reference momentum_op: velocity = mu*velocity + grad;
    param -= lr * (grad + mu*velocity) if nesterov else lr*velocity."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slot(self, p):
        return {"velocity": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        if wd:
            g = g + wd * p
        v = self.momentum * slots["velocity"] + g
        if self.use_nesterov:
            new_p = p - lr * (g + self.momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.epsilon = epsilon

    def _init_slot(self, p):
        return {"moment": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        if wd:
            g = g + wd * p
        m = slots["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self.epsilon), {"moment": m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.rho, self.epsilon, self.momentum = rho, epsilon, momentum

    def _init_slot(self, p):
        # separate arrays per slot: donation-safe (a shared buffer would be
        # donated twice in a donated train step)
        return {"mean_square": jnp.zeros_like(jnp.asarray(p), jnp.float32),
                "momentum": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        if wd:
            g = g + wd * p
        ms = self.rho * slots["mean_square"] + (1 - self.rho) * jnp.square(g)
        mom = self.momentum * slots["momentum"] + lr * g / jnp.sqrt(ms + self.epsilon)
        return p - mom, {"mean_square": ms, "momentum": mom}


class Adam(Optimizer):
    """Reference adam_op.cc (L2-coupled weight decay via weight_decay arg)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, lazy_mode=False,
                 apply_decay_param_fun=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, apply_decay_param_fun)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self._decoupled = False

    def _init_slot(self, p):
        return {"moment1": jnp.zeros_like(jnp.asarray(p), jnp.float32),
                "moment2": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        if wd and not self._decoupled:
            g = g + wd * p
        t = step.astype(jnp.float32)
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        if wd and self._decoupled:
            new_p = new_p - lr * wd * p
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference adamw_op / python adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, multi_precision=True,
                 apply_decay_param_fun=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision,
                         apply_decay_param_fun=apply_decay_param_fun)
        self._decoupled = True


class AdamMax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slot(self, p):
        return {"moment": jnp.zeros_like(jnp.asarray(p), jnp.float32),
                "inf_norm": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        if wd:
            g = g + wd * p
        t = step.astype(jnp.float32)
        m = self.beta1 * slots["moment"] + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * slots["inf_norm"], jnp.abs(g))
        new_p = p - lr / (1 - self.beta1 ** t) * m / (u + self.epsilon)
        return new_p, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    """Reference lamb_op.cc / distributed_fused_lamb_op.cu semantics: adam
    update direction scaled by trust ratio ||p|| / ||update||."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=True):
        # exclude_from_weight_decay_fn(name) -> True means wd=0 for that param
        # (reference lamb excludes LayerNorm/bias params; inverted polarity vs
        # apply_decay_param_fun, which selects params that DO get decay).
        apply_fn = None
        if exclude_from_weight_decay_fn is not None:
            apply_fn = lambda name: not exclude_from_weight_decay_fn(name)
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, multi_precision,
                         apply_decay_param_fun=apply_fn)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_fn = exclude_from_weight_decay_fn

    def _init_slot(self, p):
        return {"moment1": jnp.zeros_like(jnp.asarray(p), jnp.float32),
                "moment2": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        t = step.astype(jnp.float32)
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        update = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p - lr * trust * update, {"moment1": m, "moment2": v}


class Lars(Optimizer):
    """LARS (reference operators/optimizers/lars_momentum_op.cc +
    fleet lars meta-optimizer): momentum SGD with a layerwise-adaptive
    learning rate — local_lr = lars_coeff * ||p|| / (||g|| + wd*||p|| + eps).
    The large-batch ResNet optimizer."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=1e-9, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=True):
        apply_fn = None
        if exclude_from_weight_decay_fn is not None:
            apply_fn = lambda name: not exclude_from_weight_decay_fn(name)
        super().__init__(learning_rate, parameters, lars_weight_decay,
                         grad_clip, multi_precision,
                         apply_decay_param_fun=apply_fn)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.epsilon = epsilon

    def _init_slot(self, p):
        return {"velocity": jnp.zeros_like(jnp.asarray(p), jnp.float32)}

    def _update(self, g, p, slots, lr, step, wd):
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.lars_coeff * w_norm / (g_norm + wd * w_norm + self.epsilon),
            1.0)
        v = (self.momentum * slots["velocity"]
             + lr * local_lr * (g + wd * p))
        return p - v, {"velocity": v}


__all__.append("Lars")


class Adadelta(Optimizer):
    """Reference adadelta_op: accumulated-gradient / accumulated-update
    adaptive steps; no explicit learning-rate dependence beyond scaling."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.epsilon, self.rho = epsilon, rho

    def _init_slot(self, p):
        z = jnp.zeros_like(jnp.asarray(p), jnp.float32)
        return {"avg_squared_grad": z, "avg_squared_update": z}

    def _update(self, g, p, slots, lr, step, wd):
        if wd:
            g = g + wd * p
        eg = self.rho * slots["avg_squared_grad"] + (1 - self.rho) * jnp.square(g)
        upd = (jnp.sqrt(slots["avg_squared_update"] + self.epsilon)
               / jnp.sqrt(eg + self.epsilon)) * g
        eu = self.rho * slots["avg_squared_update"] + (1 - self.rho) * jnp.square(upd)
        return p - lr * upd, {"avg_squared_grad": eg,
                              "avg_squared_update": eu}


Adamax = AdamMax      # reference spells the public class "Adamax"
__all__ += ["Adadelta", "Adamax"]
