"""LR schedulers (reference: python/paddle/optimizer/lr.py).

Each scheduler is both stateful (``.step()``/``.get_lr()`` — dygraph parity)
and functional (``sched(step) -> lr`` with a traced step — usable inside a
jitted train step, which is how the TPU build actually runs).
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1,
                 verbose: bool = False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.step()  # advance to epoch 0, paddle semantics

    def __call__(self, step):
        """Functional form: lr at `step` (int or traced int array)."""
        return self._compute(step)

    def _compute(self, step):
        raise NotImplementedError

    def get_lr(self):
        return float(self._compute(self.last_epoch))

    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]


class NoamDecay(LRScheduler):
    """Reference lr.py NoamDecay (transformer schedule)."""

    def __init__(self, d_model: int, warmup_steps: int, learning_rate: float = 1.0,
                 last_epoch: int = -1, verbose: bool = False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        step = jnp.maximum(step, 1).astype(jnp.float32)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(a, b)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate: float, step_size: int, gamma: float = 0.1,
                 last_epoch: int = -1, verbose: bool = False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        return self.base_lr * self.gamma ** (jnp.maximum(step, 0) // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate: float, milestones, gamma: float = 0.1,
                 last_epoch: int = -1, verbose: bool = False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        ms = jnp.asarray(self.milestones)
        n = jnp.sum(jnp.maximum(step, 0) >= ms)
        return self.base_lr * self.gamma ** n


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1, verbose: bool = False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        return self.base_lr * self.gamma ** jnp.maximum(step, 0)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate: float, decay_steps: int,
                 end_lr: float = 0.0001, power: float = 1.0, cycle: bool = False,
                 last_epoch: int = -1, verbose: bool = False):
        self.decay_steps, self.end_lr, self.power = decay_steps, end_lr, power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        step = jnp.maximum(step, 0).astype(jnp.float32)
        t = jnp.minimum(step, self.decay_steps) / self.decay_steps
        return (self.base_lr - self.end_lr) * (1 - t) ** self.power + self.end_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate: float, T_max: int, eta_min: float = 0.0,
                 last_epoch: int = -1, verbose: bool = False):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        step = jnp.maximum(step, 0).astype(jnp.float32)
        cos = jnp.cos(math.pi * jnp.minimum(step, self.T_max) / self.T_max)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class LinearWarmup(LRScheduler):
    """Reference lr.py LinearWarmup — wraps another scheduler or a float."""

    def __init__(self, learning_rate, warmup_steps: int, start_lr: float,
                 end_lr: float, last_epoch: int = -1, verbose: bool = False):
        self.inner = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr, self.end_lr = start_lr, end_lr
        base = learning_rate if isinstance(learning_rate, float) else learning_rate.base_lr
        super().__init__(base, last_epoch, verbose)

    def _compute(self, step):
        step = jnp.maximum(step, 0).astype(jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            step, self.warmup_steps) / max(self.warmup_steps, 1)
        if isinstance(self.inner, LRScheduler):
            after = self.inner._compute(step - self.warmup_steps)
        else:
            after = jnp.asarray(self.inner, jnp.float32)
        return jnp.where(step < self.warmup_steps, warm, after)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch: int = -1,
                 verbose: bool = False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def _compute(self, step):
        b = jnp.asarray(self.boundaries)
        idx = jnp.sum(jnp.maximum(step, 0) >= b)
        return jnp.asarray(self.values)[idx]


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate: float, lr_lambda, last_epoch: int = -1,
                 verbose: bool = False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        return self.base_lr * self.lr_lambda(step)


class ReduceOnPlateau(LRScheduler):
    """Stateful-only (metric driven — host side by nature)."""

    def __init__(self, learning_rate: float, mode: str = "min", factor: float = 0.1,
                 patience: int = 10, threshold: float = 1e-4, cooldown: int = 0,
                 min_lr: float = 0.0, verbose: bool = False):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.cooldown, self.min_lr = threshold, cooldown, min_lr
        self._lr = learning_rate
        self._best = None
        self._bad = 0
        self._cool = 0
        super().__init__(learning_rate, -1, verbose)

    def _compute(self, step):
        return jnp.asarray(self._lr, jnp.float32)

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        m = float(metrics)
        better = (self._best is None or
                  (m < self._best - self.threshold if self.mode == "min"
                   else m > self._best + self.threshold))
        if better:
            self._best, self._bad = m, 0
        elif self._cool > 0:
            self._cool -= 1
        else:
            self._bad += 1
            if self._bad > self.patience:
                self._lr = max(self._lr * self.factor, self.min_lr)
                self._bad, self._cool = 0, self.cooldown


class NaturalExpDecay(LRScheduler):
    """lr * e^(-gamma * epoch) (reference lr.py NaturalExpDecay)."""

    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1, verbose: bool = False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        return self.base_lr * jnp.exp(-self.gamma
                                      * jnp.asarray(step, jnp.float32))


class InverseTimeDecay(LRScheduler):
    """lr / (1 + gamma * epoch) (reference lr.py InverseTimeDecay)."""

    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1, verbose: bool = False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        return self.base_lr / (1.0 + self.gamma
                               * jnp.asarray(step, jnp.float32))


class MultiplicativeDecay(LRScheduler):
    """lr * prod_{e<=epoch} lmbda(e) (reference lr.py MultiplicativeDecay).
    The running product makes this a host-side (non-traced) schedule."""

    def __init__(self, learning_rate: float, lr_lambda,
                 last_epoch: int = -1, verbose: bool = False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def _compute(self, step):
        step = int(step)
        factor = 1.0
        for e in range(1, step + 1):
            factor *= self.lr_lambda(e)
        return jnp.asarray(self.base_lr * factor, jnp.float32)
