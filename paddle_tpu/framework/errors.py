"""Enforce-style error machinery.

TPU-native analog of the reference's error taxonomy
(reference: paddle/fluid/platform/enforce.h PADDLE_ENFORCE_*, phi/core/errors.h).
Exceptions carry an error class so callers can branch on category the way the
reference's ``platform::errors::InvalidArgument`` etc. allow.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base error (reference: platform/enforce.h EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


def enforce(cond: bool, msg: str = "", exc=InvalidArgumentError) -> None:
    """PADDLE_ENFORCE analog: raise ``exc`` with ``msg`` when cond is false."""
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg: str = "") -> None:
    if a != b:
        raise InvalidArgumentError(f"expected {a!r} == {b!r}. {msg}")


def enforce_shape(x, expected_rank=None, msg: str = "") -> None:
    if expected_rank is not None and len(x.shape) != expected_rank:
        raise InvalidArgumentError(
            f"expected rank {expected_rank}, got shape {tuple(x.shape)}. {msg}")
