"""nan/inf debugging (SURVEY §5 race-detection row: the reference's
debugging aid is ``FLAGS_check_nan_inf`` checked inside
OperatorWithKernel::RunImpl, operator.cc:1252 →
details/nan_inf_utils_detail — a per-op output scan that aborts with the
offending op named).

TPU-native: per-op host checks would sync every dispatch; instead the check
compiles INTO the jitted step.  ``finite_flags`` reduces every leaf to one
boolean on device (cheap, fused); ``assert_all_finite`` reads the flags on
host and raises naming each offending leaf — same observability, one sync
per step instead of per op.  The hapi train step wires this automatically
when ``FLAGS_check_nan_inf`` is set; custom loops call these two functions
directly (or flip ``jax_debug_nans`` for the per-primitive variant).
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .flags import get_flags

__all__ = ["check_nan_inf_enabled", "finite_flags", "assert_all_finite"]


def check_nan_inf_enabled() -> bool:
    v = get_flags(["check_nan_inf"])["check_nan_inf"]
    return bool(v) and str(v) not in ("0", "False", "false")


def finite_flags(tree) -> Dict[str, Any]:
    """{leaf path: scalar bool (all finite)} — call inside jit."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        x = jnp.asarray(leaf)
        if jnp.issubdtype(x.dtype, jnp.floating):
            out[name] = jnp.all(jnp.isfinite(x))
    return out


def assert_all_finite(flags: Dict[str, Any], context: str = "") -> None:
    """Host-side: raise listing every non-finite leaf (≙ the reference's
    PADDLE_ENFORCE abort with the op name)."""
    bad = [name for name, ok in flags.items() if not bool(ok)]
    if bad:
        where = f" in {context}" if context else ""
        raise FloatingPointError(
            f"nan/inf detected{where}: {', '.join(sorted(bad)[:10])}"
            + (f" (+{len(bad) - 10} more)" if len(bad) > 10 else ""))
