"""Framework substrate: flags, errors, dtypes/devices, RNG, io.

TPU-native replacement for the reference's platform + framework layers
(SURVEY.md L0/C1-C5): there is no DeviceContext pool or allocator to manage —
XLA owns streams and buffers — so this layer reduces to configuration,
diagnostics and identity.
"""
from . import dtype, errors, flags, io, random  # noqa: F401
from .dtype import (CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace,  # noqa: F401
                    Place, TPUPlace, convert_dtype, get_device,
                    is_compiled_with_tpu, set_device)
from .errors import EnforceNotMet, enforce  # noqa: F401
from .flags import define_flag, get_flags, set_flags  # noqa: F401
from .io import load, save  # noqa: F401
from .random import seed  # noqa: F401
