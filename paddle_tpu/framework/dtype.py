"""Dtype & device helpers (reference: phi/common/{data_type,place}.h analog).

On TPU there is one accelerator device class; ``Place`` collapses to the JAX
device object. We keep a tiny facade for API parity with the reference's
CPUPlace/Place hierarchy (phi/common/place.h:109).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Canonical dtype names -> jnp dtypes (reference framework.proto VarType :118).
_DTYPE_MAP = {
    "float32": jnp.float32, "fp32": jnp.float32,
    "float64": jnp.float64, "fp64": jnp.float64,
    "float16": jnp.float16, "fp16": jnp.float16,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "int8": jnp.int8, "uint8": jnp.uint8,
    "int16": jnp.int16, "int32": jnp.int32, "int64": jnp.int64,
    "bool": jnp.bool_,
    "complex64": jnp.complex64, "complex128": jnp.complex128,
}


def convert_dtype(dtype):
    """Normalize a string / numpy / jnp dtype to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _DTYPE_MAP[dtype]
        except KeyError:
            raise ValueError(f"unknown dtype {dtype!r}") from None
    return jnp.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


class Place:
    """Device identity facade (reference phi/common/place.h)."""

    def __init__(self, device: jax.Device):
        self._device = device

    @property
    def device(self) -> jax.Device:
        return self._device

    def __repr__(self):
        return f"Place({self._device})"

    def __eq__(self, other):
        return isinstance(other, Place) and self._device == other._device


def CPUPlace() -> Place:
    return Place(jax.devices("cpu")[0])


def TPUPlace(index: int = 0) -> Place:
    devs = jax.devices()
    return Place(devs[index])


def CUDAPlace(index: int = 0) -> Place:
    """Reference CUDAPlace — on this stack "the accelerator" is the TPU;
    ported GPU scripts land on the default accelerator device
    (docs/MIGRATION.md device-mapping table)."""
    return TPUPlace(index)


def CUDAPinnedPlace() -> Place:
    return CPUPlace()      # host staging memory ≙ the host platform


def NPUPlace(index: int = 0) -> Place:
    return TPUPlace(index)


_current_device = None


def set_device(device: str):
    """paddle.set_device analog: 'cpu' | 'tpu' | 'tpu:N'."""
    global _current_device
    if device == "cpu":
        _current_device = CPUPlace()
    elif device.startswith(("tpu", "gpu", "cuda", "npu", "xpu")):
        # ported accelerator scripts (set_device('gpu')) land on the
        # default accelerator — the TPU here (docs/MIGRATION.md)
        idx = int(device.split(":")[1]) if ":" in device else 0
        _current_device = TPUPlace(idx)
    else:
        raise ValueError(f"unknown device {device!r}")
    jax.config.update("jax_default_device", _current_device.device)
    return _current_device


def get_device() -> str:
    if _current_device is None:
        d = jax.devices()[0]
    else:
        d = _current_device.device
    return f"{d.platform}:{d.id}"


def is_compiled_with_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def to_numpy(x) -> np.ndarray:
    return np.asarray(x)
