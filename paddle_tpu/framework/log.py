"""VLOG-style logging (SURVEY §5 metrics/logging: glog ``VLOG(n)`` +
fluid/log_helper.py).

``vlog(level, msg)`` emits when ``FLAGS_log_level >= level`` — level 0 is
always-on (warnings/errors go through the standard logger regardless).
"""
from __future__ import annotations

import logging
import sys
from typing import Any

from .flags import get_flags

__all__ = ["get_logger", "vlog"]

_logger = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("paddle_tpu")
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(logging.Formatter(
                "%(asctime)s [paddle_tpu] %(levelname)s %(message)s"))
            logger.addHandler(h)
            logger.setLevel(logging.INFO)
            logger.propagate = False
        _logger = logger
    return _logger


def vlog(level: int, msg: str, *args: Any) -> None:
    """Emit ``msg`` when FLAGS_log_level >= level (glog VLOG semantics)."""
    if int(get_flags(["log_level"])["log_level"]) >= level:
        get_logger().info(msg, *args)
