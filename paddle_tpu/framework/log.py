"""VLOG-style logging (SURVEY §5 metrics/logging: glog ``VLOG(n)`` +
fluid/log_helper.py).

``vlog(level, msg)`` emits when ``FLAGS_log_level >= level`` — level 0 is
always-on (warnings/errors go through the standard logger regardless).
"""
from __future__ import annotations

import logging
import sys
from typing import Any

from . import flags as _flags
from .flags import get_flags  # noqa: F401  (public re-export)

__all__ = ["get_logger", "vlog"]

_logger = None

# vlog is called on hot paths where the message is usually suppressed —
# cache the log_level flag keyed on the registry's mutation counter so a
# disabled call costs two attribute reads and a compare, not a locked
# dict-building get_flags round-trip.  set_flags/define_flag bump the
# counter, which invalidates this cache.
_cached_level = None
_cached_version = -1


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("paddle_tpu")
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(logging.Formatter(
                "%(asctime)s [paddle_tpu] %(levelname)s %(message)s"))
            logger.addHandler(h)
            logger.setLevel(logging.INFO)
            logger.propagate = False
        _logger = logger
    return _logger


def vlog(level: int, msg: str, *args: Any) -> None:
    """Emit ``msg`` when FLAGS_log_level >= level (glog VLOG semantics)."""
    global _cached_level, _cached_version
    v = _flags._version
    if v != _cached_version:
        _cached_level = int(get_flags(["log_level"])["log_level"])
        _cached_version = v
    if _cached_level >= level:
        get_logger().info(msg, *args)
