"""Global flag registry.

TPU-native analog of the reference's three-tier flag system
(reference: paddle/fluid/platform/flags.cc `PADDLE_DEFINE_EXPORTED_*`,
pybind/global_value_getter_setter.cc, env parsing in platform/init.cc:87-109).

Flags are plain python values in a process-global registry; every flag can be
seeded from the environment as ``FLAGS_<name>`` at import time, and mutated at
runtime via :func:`set_flags` (the ``paddle.set_flags`` analog).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict

_lock = threading.Lock()
_registry: Dict[str, Any] = {}
_defaults: Dict[str, Any] = {}
# bumped on every mutation — hot paths (framework.log.vlog) cache flag
# lookups keyed on this instead of taking the lock per call
_version = 0


def version() -> int:
    """Monotone counter bumped by every flag mutation (cache key for
    hot-path flag reads)."""
    return _version


def _coerce(env_value: str, default: Any) -> Any:
    if isinstance(default, bool):
        return env_value.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(env_value)
    if isinstance(default, float):
        return float(env_value)
    return env_value


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Register a flag, seeding from env var ``FLAGS_<name>`` if present."""
    global _version
    with _lock:
        if name in _registry:
            return
        value = default
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            value = _coerce(env, default)
        _registry[name] = value
        _defaults[name] = default
        _version += 1


def get_flags(names=None) -> Dict[str, Any]:
    with _lock:
        if names is None:
            return dict(_registry)
        if isinstance(names, str):
            names = [names]
        return {n: _registry[n] for n in names}


def get_flag(name: str) -> Any:
    with _lock:
        return _registry[name]


def set_flags(flags: Dict[str, Any]) -> None:
    global _version
    with _lock:
        for name, value in flags.items():
            if name not in _registry:
                raise KeyError(f"unknown flag {name!r}; define_flag it first")
            _registry[name] = value
        _version += 1


# ---------------------------------------------------------------------------
# Built-in flags (the subset of the reference's 55 exported flags that makes
# sense on TPU; reference platform/flags.cc).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Per-step nan/inf scan of outputs/grads (reference "
            "operator.cc:1252 FLAGS_check_nan_inf).")
define_flag("benchmark", False, "Synchronize after each step for timing.")
define_flag("use_pallas_kernels", True,
            "Use hand-written Pallas kernels where available (vs pure XLA).")
define_flag("pallas_interpret_routing", False,
            "Also route to Pallas kernels on non-TPU backends (interpret "
            "mode; slow — for cross-path parity testing only).")
define_flag("amp_dtype", "bfloat16", "Low-precision dtype for AMP.")
define_flag("dataloader_use_native", True,
            "Use the C++ prefetch core for DataLoader when built.")
define_flag("log_level", 0, "VLOG-style verbosity (higher = chattier).")
