"""Virtual CPU device mesh forcing (test/dryrun infrastructure).

The reference validates distributed code without a cluster by simulating
multi-node as localhost multi-process (test_dist_base.py:782); the TPU-native
analog is a multi-device CPU mesh in ONE process.  Env-var forcing
(JAX_PLATFORMS / XLA_FLAGS) is unreliable when a site hook overrides them
after the shell exports, so this forces the mesh in-process via jax.config —
which must happen before the first backend touch, with a backend reset as the
fallback when something already initialized it.
"""
from __future__ import annotations

import jax

__all__ = ["force_virtual_cpu_mesh"]


def force_virtual_cpu_mesh(n: int) -> None:
    """Make ``jax.devices()`` an ``n``-device virtual CPU mesh.

    Safe to call at any point; if an adequate CPU mesh already exists it is
    a no-op, and an initialized non-CPU backend is reset (never silently
    accepted — its devices would route Pallas kernels off interpret mode).
    """
    def _update():
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            # older jax has no in-process option for the CPU device count;
            # the XLA flag is read at (re)initialization, so setting it
            # before the first backend touch is equivalent
            import os
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count={n}"
                ).strip()

    try:
        # must run before the first backend touch — even len(jax.devices())
        # counts as one, so don't probe first
        _update()
    except RuntimeError:
        devs = jax.devices()
        if len(devs) >= n and devs[0].platform == "cpu":
            return  # an adequate CPU mesh already exists
        from jax.extend import backend as jex_backend
        jex_backend.clear_backends()
        _update()
    assert len(jax.devices()) >= n and jax.devices()[0].platform == "cpu", (
        f"could not build a {n}-device CPU mesh; have {jax.devices()}")
