"""Checkpoint save/load (reference: python/paddle/framework/io.py —
paddle.save:568 / paddle.load:784, pickle-based state_dicts; static-graph
save_persistables fluid/io.py:668).

Single-host path: numpy-ified pytrees in a pickle file.  The sharded /
re-shardable distributed checkpoint (orbax-style, the auto_parallel
converter analog) lives in paddle_tpu.distributed.checkpoint.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import fsio
from ..utils.retry import RetryPolicy, retry_call

#: Retry schedule for pickle checkpoint I/O (module-level so tests / the
#: fault harness can swap in a sleepless policy).
IO_RETRY_POLICY = RetryPolicy(max_attempts=4, base_delay=0.05)


def _to_host(obj):
    def conv(x):
        if isinstance(x, jax.Array):
            if jnp.issubdtype(x.dtype, jnp.bfloat16):
                # numpy has no bf16; stash as fp32 with a marker
                return _BF16(np.asarray(x.astype(jnp.float32)))
            return np.asarray(x)
        return x
    return jax.tree_util.tree_map(conv, obj)


class _BF16:
    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr


def _from_host(obj):
    def conv(x):
        if isinstance(x, _BF16):
            return jnp.asarray(x.arr).astype(jnp.bfloat16)
        if isinstance(x, np.ndarray):
            return jnp.asarray(x)
        return x
    return jax.tree_util.tree_map(
        conv, obj, is_leaf=lambda x: isinstance(x, _BF16))


def save(obj: Any, path: str, protocol: int = 4) -> None:
    """paddle.save analog: pickles a (nested) state_dict to path.

    The pickle is staged into ``path + ".tmp"`` (fsync'd) and
    ``os.replace``d into place, so a crash mid-save never leaves a
    torn/unloadable file at ``path``; transient I/O errors are absorbed by
    retry with backoff."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = pickle.dumps(_to_host(obj), protocol=protocol)
    retry_call(fsio.atomic_write_bytes, path, payload,
               policy=IO_RETRY_POLICY)


def load(path: str, return_numpy: bool = False) -> Any:
    """paddle.load analog."""
    obj = pickle.loads(retry_call(fsio.read_bytes, path,
                                  policy=IO_RETRY_POLICY))
    if return_numpy:
        return jax.tree_util.tree_map(
            lambda x: x.arr if isinstance(x, _BF16) else x, obj,
            is_leaf=lambda x: isinstance(x, _BF16))
    return _from_host(obj)
