"""Declarative shape/dtype inference + validation — component C8.

Reference: paddle/phi/infermeta/ (unary.cc/binary.cc/multiary.cc): every op
declares an InferMeta that validates input metas and derives output metas
BEFORE the kernel runs, so users get a typed, shaped error instead of a
kernel fault.

TPU-native role: jax already derives output shapes at trace time, so the
surviving job is the *validation* half — catch bad call shapes at the
python boundary and raise paddle-style ``InvalidArgumentError`` with the
offending shapes in the message (instead of a deep XLA trace).  The
``@infer_meta`` decorator attaches a rule to an op; rules are composed
from the small combinator set below, mirroring how the reference composes
per-op InferMeta functions from shared helpers.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import numpy as np

from .errors import InvalidArgumentError, enforce

__all__ = ["infer_meta", "Meta", "meta_of", "require_rank",
           "require_rank_in", "require_dim_match", "require_same_rank",
           "require_broadcastable", "require_floating", "require_integer"]


class Meta:
    """Shape/dtype view of one argument (the DenseTensorMeta analog)."""

    __slots__ = ("shape", "dtype", "name")

    def __init__(self, shape, dtype, name: str):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def __repr__(self):
        return f"{self.name}: {self.dtype}{list(self.shape)}"


def meta_of(x, name: str = "x") -> Optional[Meta]:
    """Meta for any array-like (Parameter, jax array, numpy, list)."""
    if x is None:
        return None
    if hasattr(x, "__jax_array__"):
        x = x.__jax_array__()
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return Meta(x.shape, x.dtype, name)
    arr = np.asarray(x)
    return Meta(arr.shape, arr.dtype, name)


# -- composable checks (≙ phi/infermeta shared helpers) ---------------------
def require_rank(m: Meta, rank: int, op: str) -> None:
    enforce(m.ndim == rank,
            f"{op}: {m.name} must be {rank}-D, got {m}",
            exc=InvalidArgumentError)


def require_rank_in(m: Meta, ranks: Sequence[int], op: str) -> None:
    enforce(m.ndim in tuple(ranks),
            f"{op}: {m.name} must have rank in {list(ranks)}, got {m}",
            exc=InvalidArgumentError)


def require_dim_match(a: Meta, da: int, b: Meta, db: int, op: str) -> None:
    enforce(a.shape[da] == b.shape[db],
            f"{op}: dim {da} of {a} must match dim {db} of {b}",
            exc=InvalidArgumentError)


def require_same_rank(a: Meta, b: Meta, op: str) -> None:
    enforce(a.ndim == b.ndim,
            f"{op}: rank mismatch between {a} and {b}",
            exc=InvalidArgumentError)


def require_broadcastable(a: Meta, b: Meta, op: str) -> None:
    try:
        np.broadcast_shapes(a.shape, b.shape)
    except ValueError:
        raise InvalidArgumentError(
            f"{op}: shapes not broadcastable: {a} vs {b}")


def require_floating(m: Meta, op: str) -> None:
    kind = np.dtype(str(m.dtype)).kind if not str(m.dtype).startswith(
        "bfloat16") else "f"
    enforce(kind == "f" or "float" in str(m.dtype) or "bf16" in str(m.dtype),
            f"{op}: {m.name} must be floating, got {m}",
            exc=InvalidArgumentError)


def require_integer(m: Meta, op: str) -> None:
    enforce(np.dtype(str(m.dtype)).kind in ("i", "u"),
            f"{op}: {m.name} must be integer, got {m}",
            exc=InvalidArgumentError)


def infer_meta(rule: Callable) -> Callable:
    """Attach a validation rule to an op: ``rule`` receives the op's
    positional/keyword arguments (arrays and attrs alike) and raises
    ``InvalidArgumentError`` on bad metas; the op body runs unchanged
    afterwards.  ``fn.__infermeta__`` exposes the rule (the analog of the
    registry linkage api.yaml ``infer_meta:`` entries give the reference).
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            rule(*args, **kwargs)
            return fn(*args, **kwargs)
        wrapped.__infermeta__ = rule
        return wrapped
    return deco
