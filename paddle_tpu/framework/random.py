"""Framework RNG: a global, splittable seed stream.

Design: JAX's functional threefry PRNG is the substrate.  For eager-mode
ergonomics (the reference's dygraph generators, paddle.seed) we keep a global
stateful *stream* of keys; for jitted training steps the user threads explicit
keys (idiomatic JAX).  The distributed RNG-state tracker that tensor
parallelism needs (reference: fleet/meta_parallel/parallel_layers/random.py:32
``RNGStatesTracker``) lives in paddle_tpu.distributed.random and builds on the
same key type.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np

_state = threading.local()


class Generator:
    """Stateful key stream over jax.random (dygraph Generator analog).

    The stream is base-key + python counter (``fold_in(key(seed), n)``), NOT
    split-and-store: under jit's trace context even ops on concrete keys
    return tracers, and storing one back into global state poisons every
    later eager call (UnexpectedTracerError).  With fold_in the only mutable
    state is a python int, which is always trace-safe."""

    def __init__(self, seed: int = 0):
        self.manual_seed(seed)

    def manual_seed(self, seed: int) -> "Generator":
        self._seed = seed
        self._base = jax.random.key(seed)
        self._count = 0
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        """Fresh key (advances the stream).

        Safe to call inside a jit trace, but the drawn key is then baked
        into the compiled program as a constant — stochastic ops in a jitted
        step should thread keys via ``key_scope`` instead (the jitted-path
        contract; see module docstring)."""
        self._count += 1
        return jax.random.fold_in(self._base, self._count)

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = state
        self._base = jax.random.key(self._seed)


def default_generator() -> Generator:
    gen = getattr(_state, "generator", None)
    if gen is None:
        gen = Generator(0)
        _state.generator = gen
    return gen


def seed(value: int) -> Generator:
    """paddle.seed analog: reseed the global generator (and numpy for host-side
    shuffling in the data pipeline)."""
    np.random.seed(value % (2 ** 32))
    return default_generator().manual_seed(value)


def next_key() -> jax.Array:
    """Fresh PRNG key from the global stream (eager-mode convenience)."""
    return default_generator().next_key()


def key_for(seed_value: Optional[int]) -> jax.Array:
    """Key from an explicit seed, or from the global stream when None."""
    if seed_value is None:
        return next_key()
    return jax.random.key(seed_value)


# ---------------------------------------------------------------------------
# Key scope: trace-safe per-op keys for jitted programs.
#
# Inside ``key_scope(step_key)`` every stochastic op (dropout etc.) draws
# ``fold_in(step_key, n)`` where n is the op's call index — deterministic by
# program position, so a jitted train step re-traced with the same key yields
# the same masks (the analog of the reference's counter-based Philox offsets,
# fused_dropout_common.h GetSeedDataAndIncrement, and the per-op seed attrs on
# fused_attention_op.cc:292-311).  Outside any scope, ops fall back to the
# global eager stream.
# ---------------------------------------------------------------------------
import contextlib  # noqa: E402


class _KeyScope:
    __slots__ = ("key", "count")

    def __init__(self, key):
        self.key = key
        self.count = 0


@contextlib.contextmanager
def key_scope(key):
    prev = getattr(_state, "key_scope", None)
    _state.key_scope = _KeyScope(key)
    try:
        yield
    finally:
        _state.key_scope = prev


# Optional provider installed by paddle_tpu.distributed.random: when a TP
# RNGStatesTracker scope is active it derives per-mesh-axis-distinct keys
# (the reference's RNGStatesTracker, fleet/meta_parallel/parallel_layers/
# random.py:32).  Receives the (possibly traced) key_scope-derived key so
# that under jit the per-step variation stays traced — the tracker only
# *adds* name/axis entropy, it never replaces a traced key with a constant.
# Returns None when no tracker scope is active.
_op_key_provider = None


def set_op_key_provider(fn):
    global _op_key_provider
    _op_key_provider = fn


def op_key() -> jax.Array:
    """Key for one stochastic op.

    Precedence: key_scope (traced, per-step) as the base; an active
    RNGStatesTracker scope folds its named-stream/axis entropy on top; with
    no key_scope the tracker draws from its own stream; with neither, the
    global eager stream."""
    scope = getattr(_state, "key_scope", None)
    scope_k = None
    if scope is not None:
        scope_k = jax.random.fold_in(scope.key, scope.count)
        scope.count += 1
    if _op_key_provider is not None:
        k = _op_key_provider(scope_k)
        if k is not None:
            return k
    if scope_k is not None:
        return scope_k
    return next_key()
