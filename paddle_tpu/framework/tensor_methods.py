"""paddle.Tensor method surface on jax arrays (reference:
python/paddle/tensor/__init__.py monkey_patch_* — the reference installs
~200 methods onto its Tensor; here the paddle-shaped methods are
installed onto ``jaxlib ArrayImpl`` AND ``jax.core.Tracer`` so the same
idioms work eagerly and inside jit traces).

Rules:
- NEVER overrides an attribute the jax types already have (reshape,
  astype, sum, mean, item, ... stay jax's own);
- methods are thin jnp delegates, so tracing semantics are untouched;
- host-only methods (``numpy``, ``cpu``) raise jax's natural
  concretization error under jit, which is the correct failure mode.

``x.stop_gradient = True`` (instance attribute mutation) cannot exist on
immutable arrays — use ``x.detach()`` / ``paddle.no_grad`` instead
(docs/MIGRATION.md: in-place ops).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .dtype import convert_dtype

__all__ = ["install_tensor_methods",
           "install_reference_method_contract",
           "INSTALLED_METHODS"]


def _numpy(self):
    return np.asarray(self)


def _detach(self):
    return jax.lax.stop_gradient(self)


def _cpu(self):
    return jax.device_put(self, jax.devices("cpu")[0])


def _cuda(self, device_id: int = 0):
    return jax.device_put(self, jax.devices()[device_id])


def _delegate(name, kind: str = "pt"):
    """Bind a PACKAGE-LEVEL paddle_tpu (or paddle_tpu.linalg) function as
    a method (single source of truth — the functional op; the reference's
    monkey_patch does exactly this with its op lambdas)."""
    def method(self, *args, **kwargs):
        import paddle_tpu as pt
        mod = pt.linalg if kind == "linalg" else pt
        return getattr(mod, name)(self, *args, **kwargs)
    method.__name__ = name
    return method


def _resolve_targets() -> list:
    """The classes the method surface lands on.  ``ArrayImpl`` lives in
    a jax-private module; a jax refactor that moves it must DEGRADE the
    install (RuntimeWarning, tracer-only surface) — never hard-fail
    ``import paddle_tpu`` (ADVICE round 5)."""
    import warnings
    targets = []
    try:
        from jax._src.array import ArrayImpl
        targets.append(ArrayImpl)
    except ImportError:
        warnings.warn(
            "paddle_tpu: jax._src.array.ArrayImpl not importable under "
            f"jax {jax.__version__} — paddle Tensor methods will be "
            "unavailable on concrete arrays (traced code is unaffected)",
            RuntimeWarning)
    tracer = getattr(jax.core, "Tracer", None)
    if tracer is not None:
        targets.append(tracer)
    else:
        warnings.warn(
            "paddle_tpu: jax.core.Tracer not found — paddle Tensor "
            "methods will be unavailable inside jit traces",
            RuntimeWarning)
    return targets


def _install(table) -> None:
    """Shared install loop: bind onto the concrete array class and the
    tracer base, never touching existing attributes; sealed-type
    failures are LOUD (a silent skip would vanish the whole surface)."""
    targets = _resolve_targets()
    failed = []
    for name, fn in table.items():
        for t in targets:
            if not hasattr(t, name):
                try:
                    setattr(t, name, fn)
                except (AttributeError, TypeError):
                    failed.append((t.__name__, name))
                    continue
                if name not in INSTALLED_METHODS:
                    INSTALLED_METHODS.append(name)
    if failed:
        import warnings
        warnings.warn(
            f"tensor-method install skipped {len(failed)} bindings "
            f"(sealed type?): {failed[:5]}...", RuntimeWarning)


def _dim(self):
    return self.ndim


def _binary(fn):
    return lambda self, y: fn(self, y)


def _unary(fn):
    return lambda self: fn(self)


_METHODS = {
    "numpy": _numpy,
    "detach": _detach,
    "cpu": _cpu,
    "cuda": _cuda,
    "cast": _delegate("cast"),
    "unsqueeze": _delegate("unsqueeze"),
    "norm": _delegate("norm"),
    "numel": _delegate("numel"),
    "dim": _dim,
    "ndimension": _dim,
    "t": _delegate("t"),
    "expand": _delegate("expand"),
    "tile": _delegate("tile"),
    "gather": _delegate("gather"),
    "allclose": _delegate("allclose"),
    # binary ops (paddle spelling)
    "add": _binary(jnp.add),
    "subtract": _binary(jnp.subtract),
    "multiply": _binary(jnp.multiply),
    "divide": _binary(jnp.divide),
    "matmul": _binary(jnp.matmul),
    "mm": _binary(jnp.matmul),
    "mod": _binary(jnp.mod),
    "pow": _binary(jnp.power),
    "maximum": _binary(jnp.maximum),
    "minimum": _binary(jnp.minimum),
    "equal": _binary(jnp.equal),
    "not_equal": _binary(jnp.not_equal),
    "greater_than": _binary(jnp.greater),
    "greater_equal": _binary(jnp.greater_equal),
    "less_than": _binary(jnp.less),
    "less_equal": _binary(jnp.less_equal),
    "logical_and": _binary(jnp.logical_and),
    "logical_or": _binary(jnp.logical_or),
    # unary math (paddle spelling)
    "abs": _unary(jnp.abs),
    "exp": _unary(jnp.exp),
    "log": _unary(jnp.log),
    "sqrt": _unary(jnp.sqrt),
    "rsqrt": _unary(jax.lax.rsqrt),
    "square": _unary(jnp.square),
    "tanh": _unary(jnp.tanh),
    "sigmoid": _unary(jax.nn.sigmoid),
    "floor": _unary(jnp.floor),
    "ceil": _unary(jnp.ceil),
    "sign": _unary(jnp.sign),
    "erf": _unary(jax.scipy.special.erf),
    "isnan": _unary(jnp.isnan),
    "isinf": _unary(jnp.isinf),
    "isfinite": _unary(jnp.isfinite),
}

INSTALLED_METHODS: list = []


def install_tensor_methods() -> None:
    """Install the method table onto the concrete array class and the
    tracer base; existing attributes are never touched.  The class is
    imported, NOT derived from a live array — materializing one here
    would initialize the backend at package-import time (and hang when
    the TPU tunnel is down)."""
    _install(_METHODS)


# The reference Tensor method contract (python/paddle/tensor/__init__.py
# ``tensor_method_func`` — the exact list the reference monkey-patches
# onto its Tensor).  Everything here that has a package-level
# counterpart (paddle_tpu.<name>, paddle_tpu.linalg.<name>, or the
# non-inplace base of a ``name_``) is auto-delegated as a method, with
# ``self`` as the first argument — byte-for-byte the reference's own
# binding rule.
_REF_TENSOR_METHODS = [
    "matmul",
    "dot",
    "cov",
    "norm",
    "cond",
    "transpose",
    "lstsq",
    "dist",
    "t",
    "cross",
    "cholesky",
    "bmm",
    "histogram",
    "bincount",
    "mv",
    "matrix_power",
    "qr",
    "eigvals",
    "eigvalsh",
    "abs",
    "acos",
    "all",
    "any",
    "asin",
    "atan",
    "ceil",
    "ceil_",
    "cos",
    "cosh",
    "cumsum",
    "cumprod",
    "logit",
    "exp",
    "exp_",
    "floor",
    "floor_",
    "increment",
    "log",
    "log2",
    "log10",
    "logsumexp",
    "multiplex",
    "pow",
    "prod",
    "reciprocal",
    "reciprocal_",
    "round",
    "round_",
    "rsqrt",
    "rsqrt_",
    "scale",
    "scale_",
    "sign",
    "sin",
    "sinh",
    "sqrt",
    "sqrt_",
    "square",
    "stanh",
    "sum",
    "nansum",
    "nanmean",
    "tanh",
    "tanh_",
    "add_n",
    "max",
    "amax",
    "maximum",
    "min",
    "amin",
    "minimum",
    "fmax",
    "fmin",
    "mm",
    "inner",
    "outer",
    "divide",
    "floor_divide",
    "remainder",
    "mod",
    "floor_mod",
    "multiply",
    "add",
    "add_",
    "subtract",
    "subtract_",
    "atan",
    "logsumexp",
    "inverse",
    "log1p",
    "erf",
    "addmm",
    "clip",
    "clip_",
    "trace",
    "kron",
    "kthvalue",
    "isfinite",
    "isinf",
    "isnan",
    "broadcast_shape",
    "conj",
    "neg",
    "lgamma",
    "equal",
    "equal_all",
    "greater_equal",
    "greater_than",
    "is_empty",
    "less_equal",
    "less_than",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "not_equal",
    "allclose",
    "isclose",
    "is_tensor",
    "cast",
    "concat",
    "expand",
    "broadcast_to",
    "expand_as",
    "flatten",
    "flatten_",
    "gather",
    "gather_nd",
    "reshape",
    "reshape_",
    "reverse",
    "scatter",
    "scatter_",
    "scatter_nd_add",
    "scatter_nd",
    "shard_index",
    "slice",
    "split",
    "chunk",
    "tensordot",
    "squeeze",
    "squeeze_",
    "stack",
    "strided_slice",
    "transpose",
    "unique",
    "unique_consecutive",
    "unsqueeze",
    "unsqueeze_",
    "unstack",
    "flip",
    "rot90",
    "unbind",
    "roll",
    "tile",
    "argmax",
    "argmin",
    "argsort",
    "masked_select",
    "topk",
    "where",
    "index_select",
    "nonzero",
    "sort",
    "index_sample",
    "mean",
    "std",
    "var",
    "numel",
    "median",
    "quantile",
    "is_complex",
    "is_integer",
    "rank",
    "shape",
    "real",
    "imag",
    "is_floating_point",
    "digamma",
    "diagonal",
    "trunc",
    "bitwise_and",
    "bitwise_or",
    "bitwise_xor",
    "bitwise_not",
    "broadcast_tensors",
    "eig",
    "uniform_",
    "multi_dot",
    "solve",
    "cholesky_solve",
    "triangular_solve",
    "asinh",
    "atanh",
    "acosh",
    "lu",
    "lu_unpack",
    "as_complex",
    "as_real",
    "rad2deg",
    "deg2rad",
    "gcd",
    "lcm",
    "diff",
    "mode",
    "lerp",
    "lerp_",
    "erfinv",
    "erfinv_",
    "angle",
    "moveaxis",
    "repeat_interleave",
    "take_along_axis",
    "put_along_axis",
    "put_along_axis_",
    "exponential_",
]


def _resolve_ref_method(name):
    import paddle_tpu as pt
    fn = getattr(pt, name, None)
    if callable(fn):
        return name, "pt"
    fn = getattr(pt.linalg, name, None)
    if callable(fn):
        return name, "linalg"
    if name.endswith("_"):
        base = name[:-1]
        if callable(getattr(pt, base, None)):
            return base, "pt"
        if callable(getattr(pt.linalg, base, None)):
            return base, "linalg"
    return None, None


# in-place method names (`add_`, `clip_`, ...) delegate to their
# non-mutating bases — immutable arrays can't be written through — so
# `x.add_(y)` computes a NEW array and the receiver is unchanged.
# Ported paddle code calling them for the side effect gets a ONE-TIME
# runtime signal instead of silence (ADVICE round 5).
_INPLACE_WARNED: set = set()


def _warn_inplace(name: str) -> None:
    if name in _INPLACE_WARNED:
        return
    _INPLACE_WARNED.add(name)
    import warnings
    warnings.warn(
        f"paddle_tpu: Tensor.{name}() cannot mutate an immutable jax "
        "array — it returns a new tensor and the receiver is unchanged; "
        "assign the result (docs/MIGRATION.md: in-place ops)",
        UserWarning, stacklevel=3)


def _inplace_delegate(name, base, kind):
    inner = _delegate(base, kind)

    def method(self, *args, **kwargs):
        _warn_inplace(name)
        return inner(self, *args, **kwargs)
    method.__name__ = name
    return method


def _uniform_(self, min=-1.0, max=1.0, seed=0):  # noqa: A002
    """Reference Tensor.uniform_(min, max, seed): a uniform fill of
    SELF's shape/dtype — must NOT fall through to the creation op
    paddle.uniform(shape, ...), whose first argument is a shape.  A
    nonzero ``seed`` is folded into a dedicated key (the reference's
    per-call seeded draw) instead of silently ignored (ADVICE round 5)."""
    _warn_inplace("uniform_")
    if seed:
        key = (jax.random.key(int(seed)) if hasattr(jax.random, "key")
               else jax.random.PRNGKey(int(seed)))
        dtype = (self.dtype if jnp.issubdtype(self.dtype, jnp.floating)
                 else jnp.float32)
        return jax.random.uniform(key, self.shape, dtype, min, max)
    import paddle_tpu as pt
    return pt.uniform(self.shape, str(self.dtype), min, max)


# in-place names whose BASE is a creation/op with a non-tensor first
# argument: auto-delegation would be semantically wrong
_REF_METHOD_OVERRIDES = {"uniform_": _uniform_}


def install_reference_method_contract() -> None:
    """Second install pass: the full reference tensor_method_func list,
    auto-delegated.  Runs AFTER the package namespace is fully built
    (end of paddle_tpu/__init__), so every functional op is resolvable."""
    table = dict(_REF_METHOD_OVERRIDES)
    for name in _REF_TENSOR_METHODS:
        if name in table:
            continue
        resolved, kind = _resolve_ref_method(name)
        if resolved is None:
            continue
        if name.endswith("_") and resolved == name[:-1]:
            # `name_` fell through to its non-mutating base: warn once
            # at first call that nothing is mutated
            table[name] = _inplace_delegate(name, resolved, kind)
        else:
            table[name] = _delegate(resolved, kind)
    _install(table)
