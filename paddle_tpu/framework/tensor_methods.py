"""paddle.Tensor method surface on jax arrays (reference:
python/paddle/tensor/__init__.py monkey_patch_* — the reference installs
~200 methods onto its Tensor; here the paddle-shaped methods are
installed onto ``jaxlib ArrayImpl`` AND ``jax.core.Tracer`` so the same
idioms work eagerly and inside jit traces).

Rules:
- NEVER overrides an attribute the jax types already have (reshape,
  astype, sum, mean, item, ... stay jax's own);
- methods are thin jnp delegates, so tracing semantics are untouched;
- host-only methods (``numpy``, ``cpu``) raise jax's natural
  concretization error under jit, which is the correct failure mode.

``x.stop_gradient = True`` (instance attribute mutation) cannot exist on
immutable arrays — use ``x.detach()`` / ``paddle.no_grad`` instead
(docs/MIGRATION.md: in-place ops).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .dtype import convert_dtype

__all__ = ["install_tensor_methods", "INSTALLED_METHODS"]


def _numpy(self):
    return np.asarray(self)


def _detach(self):
    return jax.lax.stop_gradient(self)


def _cpu(self):
    return jax.device_put(self, jax.devices("cpu")[0])


def _cuda(self, device_id: int = 0):
    return jax.device_put(self, jax.devices()[device_id])


def _delegate(name):
    """Bind the PACKAGE-LEVEL paddle_tpu function of the same name as a
    method (single source of truth — the functional op; the reference's
    monkey_patch does exactly this with its op lambdas)."""
    def method(self, *args, **kwargs):
        import paddle_tpu as pt
        return getattr(pt, name)(self, *args, **kwargs)
    method.__name__ = name
    return method


def _dim(self):
    return self.ndim


def _binary(fn):
    return lambda self, y: fn(self, y)


def _unary(fn):
    return lambda self: fn(self)


_METHODS = {
    "numpy": _numpy,
    "detach": _detach,
    "cpu": _cpu,
    "cuda": _cuda,
    "cast": _delegate("cast"),
    "unsqueeze": _delegate("unsqueeze"),
    "norm": _delegate("norm"),
    "numel": _delegate("numel"),
    "dim": _dim,
    "ndimension": _dim,
    "t": _delegate("t"),
    "expand": _delegate("expand"),
    "tile": _delegate("tile"),
    "gather": _delegate("gather"),
    "allclose": _delegate("allclose"),
    # binary ops (paddle spelling)
    "add": _binary(jnp.add),
    "subtract": _binary(jnp.subtract),
    "multiply": _binary(jnp.multiply),
    "divide": _binary(jnp.divide),
    "matmul": _binary(jnp.matmul),
    "mm": _binary(jnp.matmul),
    "mod": _binary(jnp.mod),
    "pow": _binary(jnp.power),
    "maximum": _binary(jnp.maximum),
    "minimum": _binary(jnp.minimum),
    "equal": _binary(jnp.equal),
    "not_equal": _binary(jnp.not_equal),
    "greater_than": _binary(jnp.greater),
    "greater_equal": _binary(jnp.greater_equal),
    "less_than": _binary(jnp.less),
    "less_equal": _binary(jnp.less_equal),
    "logical_and": _binary(jnp.logical_and),
    "logical_or": _binary(jnp.logical_or),
    # unary math (paddle spelling)
    "abs": _unary(jnp.abs),
    "exp": _unary(jnp.exp),
    "log": _unary(jnp.log),
    "sqrt": _unary(jnp.sqrt),
    "rsqrt": _unary(jax.lax.rsqrt),
    "square": _unary(jnp.square),
    "tanh": _unary(jnp.tanh),
    "sigmoid": _unary(jax.nn.sigmoid),
    "floor": _unary(jnp.floor),
    "ceil": _unary(jnp.ceil),
    "sign": _unary(jnp.sign),
    "erf": _unary(jax.scipy.special.erf),
    "isnan": _unary(jnp.isnan),
    "isinf": _unary(jnp.isinf),
    "isfinite": _unary(jnp.isfinite),
}

INSTALLED_METHODS: list = []


def install_tensor_methods() -> None:
    """Install the method table onto the concrete array class and the
    tracer base; existing attributes are never touched.  The class is
    imported, NOT derived from a live array — materializing one here
    would initialize the backend at package-import time (and hang when
    the TPU tunnel is down)."""
    from jax._src.array import ArrayImpl
    targets = [ArrayImpl, jax.core.Tracer]
    failed = []
    for name, fn in _METHODS.items():
        for t in targets:
            if not hasattr(t, name):
                try:
                    setattr(t, name, fn)
                except (AttributeError, TypeError):
                    failed.append((t.__name__, name))
                    continue
                if name not in INSTALLED_METHODS:
                    INSTALLED_METHODS.append(name)
    if failed:
        # a sealed type in a future jaxlib must be loud, not a silent
        # removal of the whole eager method surface
        import warnings
        warnings.warn(
            f"tensor-method install skipped {len(failed)} bindings "
            f"(sealed type?): {failed[:5]}...", RuntimeWarning)
