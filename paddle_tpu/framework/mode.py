"""Global execution-mode state (reference: paddle.enable_static /
disable_static / in_dynamic_mode, fluid/framework.py; set_grad_enabled /
is_grad_enabled, fluid/dygraph/base.py).

There is one codepath here (un-jitted JAX = dygraph, jitted = static), so
these toggles are recorded STATE, not a switch between two runtimes: the
static facade (`paddle_tpu.static`) works identically in either mode, and
ported scripts that open with ``paddle.enable_static()`` run unchanged.
Grad mode interoperates with ``paddle_tpu.no_grad``: inside a
``no_grad``/``set_grad_enabled(False)`` region ``is_grad_enabled()`` is
False and decorated functions stop gradients.
"""
from __future__ import annotations

__all__ = ["enable_static", "disable_static", "in_dynamic_mode",
           "set_grad_enabled", "is_grad_enabled"]

_static_mode = False
_grad_enabled = True


def enable_static() -> None:
    """Record static mode (reference paddle.enable_static).  The one-jit
    design needs no runtime switch; this keeps ported scripts working and
    makes ``in_dynamic_mode()`` answer like the reference."""
    global _static_mode
    _static_mode = True


def disable_static() -> None:
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def is_grad_enabled() -> bool:
    return _grad_enabled


class set_grad_enabled:
    """Context manager mirroring reference fluid/dygraph/base.py — usable
    as ``with set_grad_enabled(False): ...``; the mode applies at with-entry
    (like the reference contextmanager), nests, and is re-enterable."""

    def __init__(self, mode: bool):
        self._mode = bool(mode)
        # a stack, not a slot: the same instance may be entered while
        # already active (nested `with cm`, recursive decorated fns)
        self._prev = []

    def __enter__(self):
        global _grad_enabled
        self._prev.append(_grad_enabled)
        _grad_enabled = self._mode
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev.pop()
        return False
