"""paddle.cost_model analog (reference: python/paddle/cost_model/
cost_model.py — CostModel.profile_measure runs the program under the
profiler and returns per-op cost data).

TPU-first: the cost source is XLA itself.  ``profile_measure`` compiles the
jitted program and reads the compiler's cost analysis (flops, bytes
accessed, transcendentals) plus an optional measured wall-clock — no
separate profiler pass or per-op cost database to maintain.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Sequence

import jax

__all__ = ["CostModel"]


class CostModel:
    def profile_measure(self, fn: Callable, example_args: Sequence,
                        device: str = None,
                        fetch_cost_list=("time", "flops"),
                        measure_iters: int = 3) -> Dict[str, float]:
        """Compile ``fn(*example_args)`` and return its cost dict.

        Keys: 'flops', 'bytes_accessed', 'transcendentals' from the
        compiled program's cost analysis; 'time' (seconds/step, measured)
        when requested.  ``device`` selects the backend ('tpu'/'cpu');
        None uses the default."""
        if device is not None:
            try:
                dev = jax.devices(device)[0]
            except RuntimeError as e:
                raise RuntimeError(
                    f"device {device!r} unavailable: {e}") from e
            # placing the inputs pins the computation to the backend
            # (jit's backend= kwarg is deprecated); a zero-arg fn is
            # pinned via default_device instead
            if example_args:
                example_args = jax.device_put(tuple(example_args), dev)
            else:
                fn_orig = fn

                def fn(*a):
                    with jax.default_device(dev):
                        return fn_orig(*a)
        jitted = jax.jit(fn)
        compiled = jitted.lower(*example_args).compile()
        analyses = compiled.cost_analysis()
        ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
        out: Dict[str, float] = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            "transcendentals": float(ca.get("transcendentals", 0.0)),
        }
        if "time" in fetch_cost_list:
            r = jitted(*example_args)       # warm (compile cached above)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(measure_iters):
                r = jitted(*example_args)
            jax.block_until_ready(r)
            out["time"] = (time.perf_counter() - t0) / measure_iters
        return out
