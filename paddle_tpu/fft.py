"""paddle.fft parity (reference python/paddle/fft.py — spectral ops over
the phi fft kernels).  On TPU the substrate is jnp.fft: XLA lowers FFTs
natively (and falls back to a DUCC custom call on CPU); the paddle surface
is norm/axis argument order, kept here verbatim."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else x


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(_arr(x), n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(_arr(x), n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(_arr(x), n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(_arr(x), n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(_arr(x), n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(_arr(x), n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(_arr(x), s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(_arr(x), s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(_arr(x), s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(_arr(x), s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(_arr(x), s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(_arr(x), s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(_arr(x), s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(_arr(x), s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype="float32"):
    return jnp.fft.fftfreq(n, d=d).astype(dtype)


def rfftfreq(n, d=1.0, dtype="float32"):
    return jnp.fft.rfftfreq(n, d=d).astype(dtype)


def fftshift(x, axes=None):
    return jnp.fft.fftshift(_arr(x), axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(_arr(x), axes=axes)


def _inv_norm(norm: str) -> str:
    """hfftn(x, norm) == irfftn(conj(x), swapped norm) (scipy identity:
    the hermitian transform swaps the forward/backward scaling)."""
    return {"backward": "forward", "forward": "backward",
            "ortho": "ortho"}[norm]


def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfftn(jnp.conj(jnp.asarray(x)), s=s, axes=axes,
                          norm=_inv_norm(norm))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.conj(jnp.fft.rfftn(jnp.asarray(x), s=s, axes=axes,
                                  norm=_inv_norm(norm)))


def hfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(jnp.conj(jnp.asarray(x)), s=s, axes=axes,
                          norm=_inv_norm(norm))


def ihfftn(x, s=None, axes=None, norm="backward"):
    return jnp.conj(jnp.fft.rfftn(jnp.asarray(x), s=s, axes=axes,
                                  norm=_inv_norm(norm)))
